"""Pipeline parallelism, compiled (GPipe and 1F1B schedules in one XLA
program).

The reference implements PP as a Python runtime: PipelineLayer stage
partitioning + 1F1B/interleave schedulers exchanging activations over NCCL
p2p (reference: .../meta_parallel/pipeline_parallel.py:440
forward_backward_pipeline, :906 PipelineParallelWithInterleave,
pp_layers.py:92 SegmentLayers, pp_utils/p2p_communication.py:313), plus an
actor-based static-mode runtime (fleet_executor Carrier/Interceptor,
SURVEY.md §2.5).

TPU-native replacement (SURVEY.md §7 "hardest parts" #2): the schedule is
DATA, not control flow. The decoder stack's per-layer params are stacked
with a leading layer dim, reshaped to (stages, layers_per_stage, ...) with
the stage dim sharded over the mesh's 'pp' axis. `jnp.roll` on the
stage-sharded activation buffer hands microbatches to the next stage as an
ICI collective-permute; `vmap(stage_fn)` over the stage dim becomes
per-device stage compute under GSPMD.

Two schedules:

- "gpipe": one `lax.scan` over M+S-1 forward ticks; backward is jax.grad
  through the scan (XLA schedules the reverse pipeline). Simple, but the
  autodiff of the scan saves the full carry at every tick — activation
  memory grows with M — and the reversed scan drags the dynamic-update
  chains of the output buffer through AD.

- "1f1b" (default): hand-rolled forward AND backward as three scans —
  warmup (S-1 forward-only ticks), steady (M ticks, each one Forward for
  the entering microbatch and one Backward for the leaving one — the
  classic one-forward-one-backward interleaving), drain (S-1
  backward-only ticks). Per-stage inputs are saved in a CIRCULAR buffer
  of depth min(M, 2S-1) — the true 1F1B in-flight bound (reference
  pipeline_parallel.py:440 keeps at most #warmup+1 activations alive) —
  and each stage's backward recomputes its forward from the saved input
  (per-stage remat, same FLOPs as the gpipe+remat path). Wall ticks:
  (S-1)·F + M·(F+B) + (S-1)·B = the classical (M+S-1)(F+B) pipeline
  critical path, with no autodiff-of-scan overhead.

Stage partitioning is generic (SegmentLayers equivalent): the trainer
auto-detects the model's longest LayerList of structurally-identical
layers (Llama's model.layers, BERT's encoder stack, any custom stack).
Since r5, layers need NOT divide evenly: uneven splits — uniform-uneven
(layers % stages != 0) or explicit SegmentLayers-style
`stage_boundaries` (reference pp_layers.py:92) — pad the short stages
with masked identity slots (zero params, zero grads; compute waste
bounded by (S*K - L)/L). Tied embeddings (SharedLayerDesc,
pp_layers.py:76) come free: the head falls back to the embedding
weight's transpose and autodiff sums both stages' contributions into
the one shared weight. VPP interleave still needs
layers % (pp * interleave) == 0. Embedding and loss head are
overridable callables for non-Llama models.
"""
from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Callable

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.jit.functional import functional_call, state_tensors
from paddle_tpu.parallel.plan import ShardingPlan
from paddle_tpu.parallel.trainer import Trainer, TrainStepConfig, _cast_tree

STACK_PREFIX = "pipeline.layers::"
# pseudo-entry riding in the staged param dict for uneven splits: the
# (S, k) bool validity mask (padded slots run as identity)
_VALID_KEY = "__stage_valid__"


def detect_layer_stack(model):
    """Find the pipeline-able layer stack: the longest LayerList (>= 2
    sublayers) whose sublayers all expose the same parameter structure.
    Returns (qualified name, LayerList). SegmentLayers equivalent
    (reference pp_layers.py:92) for arbitrary models."""
    from paddle_tpu.nn.layer.container import LayerList

    best = None
    for name, sub in model.named_sublayers():
        if not isinstance(sub, LayerList) or len(sub) < 2:
            continue
        shapes = [
            tuple(sorted((n, tuple(t._value.shape))
                         for n, t in state_tensors(l).items()))
            for l in sub]
        if any(s != shapes[0] for s in shapes[1:]):
            continue
        if best is None or len(sub) > len(best[1]):
            best = (name, sub)
    if best is None:
        raise ValueError(
            "no pipeline-able LayerList found: the model needs a stack of "
            ">=2 structurally-identical layers (e.g. decoder layers)")
    return best


class PipelinePlan(ShardingPlan):
    """Wraps a base plan: stacked layer params get 'pp' prepended on the
    layer/stage dim; everything else falls through."""

    def __init__(self, base: ShardingPlan):
        self.base = base
        self.rules = base.rules
        self.default = base.default

    def spec_for(self, name: str, ndim: int | None = None) -> P:
        if name.startswith(STACK_PREFIX):
            local = name[len(STACK_PREFIX):]
            sub = self.base.spec_for(local)
            return P("pp", *tuple(sub))
        return self.base.spec_for(name)


@dataclass
class PipelineConfig(TrainStepConfig):
    num_microbatches: int = 4
    schedule: str = "1f1b"            # "1f1b" | "gpipe"
    interleave: int = 1               # virtual stages per device (VPP)
    # SegmentLayers-style custom stage split (reference pp_layers.py:92):
    # len S+1 ascending boundaries over the layer stack, e.g. (0, 3, 6,
    # 8, 10) puts layers [0,3) on stage 0 etc. None = uniform — which,
    # since r5, also handles layers % stages != 0 by padding the short
    # stages (masked identity slots, see _stage_view).
    stage_boundaries: tuple | None = None

    def __post_init__(self):
        if self.schedule not in ("1f1b", "gpipe"):
            raise ValueError(
                f"unknown pipeline schedule {self.schedule!r}: "
                "expected '1f1b' or 'gpipe'")
        if self.interleave < 1:
            raise ValueError("interleave must be >= 1")
        if self.interleave > 1 and self.schedule != "1f1b":
            raise ValueError(
                "interleave (virtual pipeline) requires schedule='1f1b'")
        if self.stage_boundaries is not None:
            b = tuple(self.stage_boundaries)
            if len(b) < 2 or b[0] != 0 or any(
                    y <= x for x, y in zip(b, b[1:])):
                raise ValueError(
                    "stage_boundaries must be ascending and start at 0, "
                    f"got {b}")
            if self.interleave > 1:
                raise ValueError(
                    "stage_boundaries does not compose with interleave "
                    "(VPP chunks need a uniform stack split)")


def build_interleaved_schedule(S: int, v: int, M: int):
    """Lockstep tick tables for the interleaved-1F1B (VPP) schedule.

    Layer chunks: the L layers split into S*v chunks; global stage
    q = l*S + s (device s, local chunk l), so activations make v laps of
    the same device ring — forward roll(+1) / backward roll(-1) — with
    the chunk index incrementing at each S-1 -> 0 wrap. Device s's unit
    order is the Megatron chunk-level order (reference
    pipeline_parallel.py:906 PipelineParallelWithInterleave /
    _get_virtual_pp_rank): microbatches in groups of S, each group
    walking chunks 0..v-1 forward and v-1..0 backward. In lockstep ticks
    this puts device s's forward unit k at tick k+s and its backward
    unit b at tick (vS-1)+(S-1-s)+b — which reproduces the Megatron
    per-device warmup counts 2(S-1-s)+(v-1)S and, at v=1, is exactly the
    plain-1F1B schedule of `_pipeline_1f1b_grads`.

    Returns (tables, T, warm_end, steady_end, C): per-tick (T, S) arrays
    f_l/f_slot/f_valid + b_l/b_slot/b_valid (chunk index, saved-input
    slot, validity for the forward/backward unit of each device), (T,)
    arrays inject_*/tail_*/emb_* (stage-0 fresh-microbatch injection,
    stage-(S-1) loss-tail microbatch, stage-0 embedding-cotangent
    capture), phase boundaries (ticks [0,warm_end) are forward-only,
    [warm_end,steady_end) mixed, [steady_end,T) backward-only), and C
    the saved-activation slots per device (greedy reuse; equals the
    Megatron in-flight bound, <= (v+1)S-1)."""
    import heapq

    if M % S != 0:
        raise ValueError(
            f"interleaved pipeline needs num_microbatches % pp == 0 "
            f"(got M={M}, pp={S})")
    Sv = S * v
    total = M * v                          # units per device
    T = M * v + (v + 1) * S - 2
    warm_end = v * S - 1
    steady_end = M * v + S - 1
    tab = {
        "f_l": np.zeros((T, S), np.int32),
        "f_slot": np.zeros((T, S), np.int32),
        "f_valid": np.zeros((T, S), bool),
        "b_l": np.zeros((T, S), np.int32),
        "b_slot": np.zeros((T, S), np.int32),
        "b_valid": np.zeros((T, S), bool),
        "inject_m": np.zeros(T, np.int32),
        "inject_valid": np.zeros(T, bool),
        "tail_m": np.zeros(T, np.int32),
        "tail_valid": np.zeros(T, bool),
        "emb_m": np.zeros(T, np.int32),
        "emb_valid": np.zeros(T, bool),
    }
    free = [list(range(total)) for _ in range(S)]
    slot_of: dict = {}
    high = 0
    for t in range(T):
        for s in range(S):               # forwards first (alloc slots)
            k = t - s
            if not (0 <= k < total):
                continue
            g, j = divmod(k, Sv)
            l, mloc = divmod(j, S)
            slot = heapq.heappop(free[s])
            slot_of[(s, k)] = slot
            high = max(high, slot + 1)
            tab["f_l"][t, s] = l
            tab["f_slot"][t, s] = slot
            tab["f_valid"][t, s] = True
            if s == 0 and l == 0:
                tab["inject_m"][t] = g * S + mloc
                tab["inject_valid"][t] = True
            if s == S - 1 and l == v - 1:
                tab["tail_m"][t] = g * S + mloc
                tab["tail_valid"][t] = True
        for s in range(S):               # then backwards (free slots)
            b = t - (v + 1) * S + s + 2
            if not (0 <= b < total):
                continue
            g, j = divmod(b, Sv)
            jl, mloc = divmod(j, S)
            lb = v - 1 - jl
            k_fwd = g * Sv + lb * S + mloc
            slot = slot_of.pop((s, k_fwd))
            heapq.heappush(free[s], slot)
            tab["b_l"][t, s] = lb
            tab["b_slot"][t, s] = slot
            tab["b_valid"][t, s] = True
            if s == 0 and lb == 0:
                tab["emb_m"][t] = g * S + mloc
                tab["emb_valid"][t] = True
    assert not slot_of, "schedule left un-backwarded units"
    return tab, T, warm_end, steady_end, high


class PipelineTrainer(Trainer):
    """Trainer whose detected layer stack runs under a compiled pipeline
    schedule.

    embed_fn(other_params, batch) -> (B, S, D) hidden states and
    tail_fn(other_params, h, batch) -> scalar mean loss are overridable;
    the defaults implement the Llama shape (embed_tokens / final norm /
    lm_head-or-tied-embedding + shifted next-token CE). NOTE: a custom
    tail_fn receives one microbatch under schedule='1f1b' and the whole
    batch under 'gpipe'; 1f1b weights the per-microbatch means equally
    (mean-of-means), while the default tail normalizes by the GLOBAL
    valid-token count under both schedules.
    """

    def __init__(self, model, optimizer, mesh, plan,
                 config: PipelineConfig | None = None,
                 embed_fn: Callable | None = None,
                 tail_fn: Callable | None = None):
        base_name, stack = detect_layer_stack(model)
        self._tpl_layer = stack[0]
        self._layers_base = base_name
        self._num_layers = len(stack)
        pat = re.compile(rf"^{re.escape(base_name)}\.(\d+)\.(.+)$")
        groups: dict[str, dict[int, str]] = {}
        for name in state_tensors(model):
            m = pat.match(name)
            if m:
                groups.setdefault(m.group(2), {})[int(m.group(1))] = name
        self._layer_groups = groups
        self._embed_fn = embed_fn
        self._tail_fn = tail_fn
        cfg = config or PipelineConfig()
        super().__init__(model, optimizer, mesh=mesh,
                         plan=PipelinePlan(plan), config=cfg)

    # -- stage partitioning (SegmentLayers equivalent) ---------------------
    def _compute_slots(self):
        """Map stage slots to stack layers. Returns (slot_layers: tuple
        of layer-index-or-negative per padded row, K: slots per stage,
        valid: (S, K) bool np mask, even: bool fast-path flag)."""
        L = self._num_layers
        S = self.mesh.shape["pp"] if self.mesh is not None \
            and "pp" in self.mesh.shape else 1
        b = self.config.stage_boundaries
        if b is not None:
            b = tuple(b)
            if len(b) != S + 1 or b[-1] != L:
                raise ValueError(
                    f"stage_boundaries needs len pp+1={S + 1} ending at "
                    f"{L} layers, got {b}")
        elif L % S == 0:
            k = L // S
            return tuple(range(L)), k, np.ones((S, k), bool), True
        else:
            # uniform-uneven: first (L % S) stages get one extra layer
            q, r = divmod(L, S)
            b, acc = [0], 0
            for i in range(S):
                acc += q + (1 if i < r else 0)
                b.append(acc)
        sizes = [b[i + 1] - b[i] for i in range(S)]
        k = max(sizes)
        slot_layers, valid = [], np.zeros((S, k), bool)
        for i in range(S):
            for j in range(k):
                if j < sizes[i]:
                    slot_layers.append(b[i] + j)
                    valid[i, j] = True
                else:
                    slot_layers.append(-1)       # padded identity slot
        return tuple(slot_layers), k, valid, False

    # -- stacked state ----------------------------------------------------
    def _init_state(self):
        (self._slot_layers, self._stage_k, self._valid_mask,
         self._even_stages) = self._compute_slots()
        if not self._even_stages and self.config.interleave > 1:
            raise ValueError(
                "interleave (VPP) needs layers % (pp * interleave) == 0; "
                "uneven/custom stage splits are plain-1F1B only")
        tensors = state_tensors(self.model)
        stacked = {}
        consumed = set()
        for local, by_idx in self._layer_groups.items():
            names = [by_idx[i] for i in range(self._num_layers)]
            rows = [tensors[n]._value for n in names]
            if self._even_stages:
                stacked[STACK_PREFIX + local] = jnp.stack(rows)
            else:
                # padded storage, ordered by stage assignment: row s*K+j
                # holds its stage's j-th layer or zeros (masked slots
                # contribute zero grads; see _stage_fwd). Keeps the
                # stacked dim divisible by pp so P('pp') shards evenly.
                zero = jnp.zeros_like(rows[0])
                stacked[STACK_PREFIX + local] = jnp.stack(
                    [rows[li] if li >= 0 else zero
                     for li in self._slot_layers])
            consumed.update(names)
        self.params = {n: t._value for n, t in tensors.items()
                       if n not in consumed}
        self.params.update(stacked)
        trainable = {n for n, t in tensors.items() if not t.stop_gradient}
        self.param_names = [n for n in self.params
                            if n.startswith(STACK_PREFIX)
                            or n in trainable]
        self.opt_state = self.optimizer.init_state_arrays(
            {n: self.params[n] for n in self.param_names})
        if self.mesh is not None and self.plan is not None:
            self._shard_state()

    def sync_to_model(self):
        tensors = state_tensors(self.model)
        for n, arr in self.params.items():
            if n.startswith(STACK_PREFIX):
                local = n[len(STACK_PREFIX):]
                by_idx = self._layer_groups[local]
                if self._even_stages:
                    for i, name in sorted(by_idx.items()):
                        tensors[name]._value = arr[i]
                else:
                    for row, li in enumerate(self._slot_layers):
                        if li >= 0:
                            tensors[by_idx[li]]._value = arr[row]
            else:
                tensors[n]._value = arr
        return self.model

    # -- shared pipeline machinery ----------------------------------------
    def _pipeline_common(self, params_c, batch):
        """Shared 1F1B/VPP prologue: split params, embed the whole batch,
        carve it into microbatches, pick the tail/weight fns, and compute
        the global loss normalizer W (sum of per-microbatch valid-token
        counts, so ragged -100 padding weighs exactly like the
        gpipe/global-mean path). Returns a namespace consumed by both
        schedule implementations — fixes here apply to both."""
        from types import SimpleNamespace

        mesh = self.mesh
        M = self.config.num_microbatches
        other, stacked = self._split_params(params_c)
        embed = self._embed_fn or self._default_embed
        if self._tail_fn is not None:
            # custom tails return a per-microbatch MEAN: weight each
            # microbatch equally (documented mean-of-means contract)
            tail_sum = self._tail_fn
            weight_fn = lambda b: jnp.asarray(1.0, jnp.float32)  # noqa: E731
        else:
            tail_sum = self._default_tail_sum
            weight_fn = self._default_tail_weight

        emb = embed(other, batch)
        B, S_len, D = emb.shape
        assert B % M == 0, f"batch {B} not divisible by microbatches {M}"
        mb = B // M
        x_mb = emb.reshape(M, mb, S_len, D)
        # only entries with a leading batch dim split into microbatches;
        # anything else (scalars, (S,) position tables, ...) is passed
        # whole to every microbatch, matching the gpipe path
        batch_r = {k: v.reshape((M, mb) + v.shape[1:])
                   for k, v in batch.items()
                   if getattr(v, "ndim", 0) >= 1 and v.shape[0] == B}
        batch_shared = {k: v for k, v in batch.items() if k not in batch_r}

        def mb_batch_at(m):
            out = {k: jax.lax.dynamic_index_in_dim(v, m, 0, keepdims=False)
                   for k, v in batch_r.items()}
            out.update(batch_shared)
            return out

        W = jnp.maximum(
            sum(weight_fn(mb_batch_at(m)) for m in range(M)), 1.0)

        dp = tuple(a for a in ("dp", "fsdp") if a in mesh.axis_names)

        def shard(x, spec):
            return jax.lax.with_sharding_constraint(
                x, NamedSharding(mesh, spec))

        return SimpleNamespace(
            other=other, stacked=stacked, embed=embed, tail_sum=tail_sum,
            emb=emb, B=B, S_len=S_len, D=D, mb=mb, x_mb=x_mb,
            mb_batch_at=mb_batch_at, W=W,
            state_spec=P("pp", dp if dp else None),
            saved_spec=P("pp", None, dp if dp else None), shard=shard)

    def _pipeline_epilogue(self, ctx, batch, grads_st, grads_other,
                           g_emb, unstage):
        """Shared 1F1B/VPP epilogue: one fused embedding vjp over the
        whole batch, then grads assembly ((stacked stage grads -> (L, ...)
        via `unstage`) + non-stack params)."""
        _, evjp = jax.vjp(lambda o: ctx.embed(o, batch), ctx.other)
        (g_o_emb,) = evjp(
            g_emb.reshape(ctx.B, ctx.S_len, ctx.D).astype(ctx.emb.dtype))
        grads_other = jax.tree.map(
            lambda a, g: a + g.astype(a.dtype), grads_other, g_o_emb)
        grads = {STACK_PREFIX + n: unstage(v)
                 for n, v in grads_st.items()}
        grads.update({n: grads_other[n] for n in self.param_names
                      if not n.startswith(STACK_PREFIX)})
        return grads

    def _split_params(self, params_c):
        other = {n: v for n, v in params_c.items()
                 if not n.startswith(STACK_PREFIX)}
        stacked = {n[len(STACK_PREFIX):]: v for n, v in params_c.items()
                   if n.startswith(STACK_PREFIX)}
        return other, stacked

    def _stage_view(self, stacked, n_pp):
        """(S*k, ...) -> (S, k, ...), stage dim sharded over 'pp'. For
        uneven splits the dict also carries the (S, k) validity mask as
        a pseudo-entry consumed by _stage_fwd (padded slots are identity
        passthroughs)."""
        k = self._stage_k
        out = {
            n: jax.lax.with_sharding_constraint(
                v.reshape((n_pp, k) + v.shape[1:]),
                NamedSharding(self.mesh, P("pp")))
            for n, v in stacked.items()}
        if not self._even_stages:
            out[_VALID_KEY] = jax.lax.with_sharding_constraint(
                jnp.asarray(self._valid_mask),
                NamedSharding(self.mesh, P("pp")))
        return out

    def _layer_apply(self, layer_params: dict, h):
        """One stack layer, functional (template-layer swap)."""
        out = functional_call(self._tpl_layer, layer_params,
                              Tensor(h, stop_gradient=False))
        return out._value if isinstance(out, Tensor) else out

    def _stage_fwd(self, stage_params, h):
        stage_params = dict(stage_params)
        valid = stage_params.pop(_VALID_KEY, None)

        def body(hh, one_layer):
            if valid is None:
                return self._layer_apply(one_layer, hh), None
            ok, lp = one_layer
            y = self._layer_apply(lp, hh)
            # padded slot: identity. where()'s zero cotangent keeps the
            # dummy zero params' grads exactly zero.
            return jnp.where(ok, y, hh), None

        xs = stage_params if valid is None else (valid, stage_params)
        out, _ = jax.lax.scan(body, h, xs)
        return out

    def _module_by_name(self, name):
        for n, sub in self.model.named_sublayers():
            if n == name:
                return sub
        raise KeyError(name)

    # -- default (Llama-shaped) embedding + loss head ----------------------
    def _default_embed(self, other, batch):
        prefix = self._embed_prefix()
        mod = self._module_by_name(prefix)
        return functional_call(
            mod, {"weight": other[f"{prefix}.weight"]},
            Tensor(batch["input_ids"], stop_gradient=True))._value

    def _tail_per_token(self, other, h, batch):
        """Final norm + head + shifted next-token CE, UNreduced:
        (per-token loss (B, S) f32, keep mask (B, S))."""
        norm_prefix = self._norm_prefix()
        mod = self._module_by_name(norm_prefix)
        h = functional_call(mod, {"weight": other[f"{norm_prefix}.weight"]},
                            Tensor(h, stop_gradient=False))._value
        logits = self._head_logits(other, h)
        labels = batch["labels"]
        # shift the labels, not the logits: slicing logits[:, :-1] copies
        # the (B*S, vocab) tensor (see models/llama.py next_token_loss).
        lf = logits.astype(jnp.float32)
        shifted = jnp.concatenate(
            [labels[:, 1:],
             jnp.full((labels.shape[0], 1), -100, labels.dtype)], axis=1)
        keep = shifted != -100
        logz = jax.nn.logsumexp(lf, axis=-1)
        tgt = jnp.take_along_axis(
            lf, jnp.where(keep, shifted, 0)[..., None].astype(jnp.int32),
            axis=-1)[..., 0]
        return (logz - tgt) * keep, keep

    def _default_tail(self, other, h, batch):
        """Global masked mean (gpipe path: whole batch in one call)."""
        if batch.get("labels") is None:
            return jnp.zeros((), jnp.float32)
        per, keep = self._tail_per_token(other, h, batch)
        return (per.sum()
                / jnp.maximum(keep.sum(), 1)).astype(jnp.float32)

    def _default_tail_sum(self, other, h, batch):
        """Per-microbatch loss SUM (1f1b path: normalized by the global
        valid-token count so ragged -100 padding weighs exactly like the
        gpipe/global-mean path)."""
        if batch.get("labels") is None:
            return jnp.zeros((), jnp.float32)
        per, _ = self._tail_per_token(other, h, batch)
        return per.sum().astype(jnp.float32)

    def _default_tail_weight(self, batch):
        """Valid-token count for one microbatch, from labels alone."""
        labels = batch.get("labels")
        if labels is None:
            return jnp.asarray(1.0, jnp.float32)
        shifted = jnp.concatenate(
            [labels[:, 1:],
             jnp.full((labels.shape[0], 1), -100, labels.dtype)], axis=1)
        return (shifted != -100).sum().astype(jnp.float32)

    def _embed_prefix(self):
        for n in self.params:
            if n.endswith("embed_tokens.weight"):
                return n[: -len(".weight")]
        raise KeyError("embed_tokens.weight not found (pass embed_fn= for "
                       "non-Llama models)")

    def _norm_prefix(self):
        cands = [n for n in self.params
                 if n.endswith(".norm.weight")
                 and not n.startswith(STACK_PREFIX)]
        return cands[0][: -len(".weight")]

    def _head_logits(self, other, h):
        name = next((n for n in other if n.endswith("lm_head.weight")),
                    None)
        if name is not None:
            return jnp.einsum("bsd,dv->bsv", h, other[name])
        w = other[f"{self._embed_prefix()}.weight"]
        return jnp.einsum("bsd,vd->bsv", h, w)

    # -- gpipe: forward scan, backward via jax.grad ------------------------
    def _loss_from_batch(self, params_c, batch):
        mesh = self.mesh
        n_pp = mesh.shape["pp"]
        M = self.config.num_microbatches

        input_ids = batch["input_ids"]
        B = input_ids.shape[0]
        assert B % M == 0, f"batch {B} not divisible by microbatches {M}"

        other, stacked = self._split_params(params_c)
        staged = self._stage_view(stacked, n_pp)

        embed = self._embed_fn or self._default_embed
        tail = self._tail_fn or self._default_tail
        emb = embed(other, batch)
        D = emb.shape[-1]
        S_len = emb.shape[1]
        mb = B // M
        x_mb = emb.reshape(M, mb, S_len, D)

        dp_axes = tuple(a for a in ("dp", "fsdp") if a in mesh.axis_names)
        state_spec = P("pp", dp_axes if dp_axes else None)

        stage_fn = jax.checkpoint(self._stage_fwd)

        def tick(carry, t):
            state, outputs = carry
            inject = jax.lax.dynamic_index_in_dim(
                x_mb, jnp.clip(t, 0, M - 1), axis=0, keepdims=False)
            state = state.at[0].set(
                jnp.where(t < M, inject, state[0]))
            state = jax.lax.with_sharding_constraint(
                state, NamedSharding(mesh, state_spec))
            y = jax.vmap(stage_fn)(staged, state)
            y = jax.lax.with_sharding_constraint(
                y, NamedSharding(mesh, state_spec))
            out_t = y[-1]
            oidx = jnp.clip(t - (n_pp - 1), 0, M - 1)
            outputs = jax.lax.dynamic_update_index_in_dim(
                outputs,
                jnp.where(t >= n_pp - 1,
                          out_t,
                          jax.lax.dynamic_index_in_dim(
                              outputs, oidx, 0, keepdims=False)),
                oidx, 0)
            state = jnp.roll(y, 1, axis=0)
            return (state, outputs), None

        T = M + n_pp - 1
        state0 = jnp.zeros((n_pp, mb, S_len, D), emb.dtype)
        outputs0 = jnp.zeros((M, mb, S_len, D), emb.dtype)
        (_, outputs), _ = jax.lax.scan(
            tick, (state0, outputs0), jnp.arange(T))

        h = outputs.reshape(B, S_len, D)
        return tail(other, h, batch)

    # -- 1f1b: hand-rolled warmup / steady / drain scans -------------------
    def _build_step(self, batch_treedef):
        if self.config.schedule != "1f1b":
            return super()._build_step(batch_treedef)
        if self.config.grad_accum_steps > 1:
            raise NotImplementedError(
                "schedule='1f1b' does not compose with grad_accum_steps; "
                "raise num_microbatches instead (pipeline microbatching "
                "IS gradient accumulation)")
        grads_fn = (self._pipeline_vpp_grads if self.config.interleave > 1
                    else self._pipeline_1f1b_grads)

        def step(params, opt_state, lr, batch):
            with self._precision_ctx():
                params_c = _cast_tree(params, self.config.compute_dtype)
                loss, grads = grads_fn(params_c, batch)
                return self._apply_update(loss, grads, params, opt_state,
                                          lr)

        return self._jit_step(step)

    def _pipeline_1f1b_grads(self, params_c, batch):
        """One-forward-one-backward compiled schedule. Returns
        (mean loss, grads dict over self.param_names). See module
        docstring; reference: pipeline_parallel.py:440
        forward_backward_pipeline (1F1B steady state), here as data —
        warmup/steady/drain lax.scans with a circular stage-input buffer
        and per-stage recompute (jax.vjp) in the backward phase."""
        mesh = self.mesh
        S = mesh.shape["pp"]
        M = self.config.num_microbatches
        assert M >= 1

        ctx = self._pipeline_common(params_c, batch)
        other, tail_sum = ctx.other, ctx.tail_sum
        emb, mb, S_len, D = ctx.emb, ctx.mb, ctx.S_len, ctx.D
        x_mb, mb_batch_at, W = ctx.x_mb, ctx.mb_batch_at, ctx.W
        state_spec, saved_spec, shard = (ctx.state_spec, ctx.saved_spec,
                                         ctx.shard)
        staged = self._stage_view(ctx.stacked, S)
        C = min(M, 2 * S - 1)   # 1F1B in-flight bound per stage
        sidx = jnp.arange(S)

        def f_phase(t, state, saved):
            inject = jax.lax.dynamic_index_in_dim(
                x_mb, jnp.clip(t, 0, M - 1), 0, keepdims=False)
            state = state.at[0].set(jnp.where(t < M, inject, state[0]))
            state = shard(state, state_spec)
            f_mb = t - sidx
            valid_f = jnp.logical_and(f_mb >= 0, f_mb < M)

            def save_one(saved_s, h_s, fm, ok):
                slot = jnp.mod(fm, C)
                old = jax.lax.dynamic_index_in_dim(saved_s, slot, 0,
                                                   keepdims=False)
                return jax.lax.dynamic_update_index_in_dim(
                    saved_s, jnp.where(ok, h_s, old), slot, 0)

            saved = jax.vmap(save_one)(saved, state, f_mb, valid_f)
            saved = shard(saved, saved_spec)
            y = jax.vmap(self._stage_fwd)(staged, state)
            y = shard(y, state_spec)
            return jnp.roll(y, 1, axis=0), saved, y

        def b_phase(t, saved, g_in, acc):
            grads_st, grads_other, g_emb = acc
            b_mb = t - 2 * (S - 1) + sidx
            valid_b = jnp.logical_and(b_mb >= 0, b_mb < M)

            def get_one(saved_s, bm):
                return jax.lax.dynamic_index_in_dim(
                    saved_s, jnp.mod(bm, C), 0, keepdims=False)

            h_saved = jax.vmap(get_one)(saved, b_mb)

            def one_bwd(stage_params, h_in, g):
                sp = dict(stage_params)
                ok = sp.pop(_VALID_KEY, None)

                def fwd(p, h):
                    if ok is not None:
                        p = dict(p)
                        p[_VALID_KEY] = ok      # closed over: no bool grad
                    return self._stage_fwd(p, h)

                _, vjp = jax.vjp(fwd, sp, h_in)
                gp, gx = vjp(g)
                return gp, gx

            gp, gx = jax.vmap(one_bwd)(staged, h_saved, g_in)

            def mask_acc(acc_a, g):
                m = valid_b.reshape((S,) + (1,) * (g.ndim - 1))
                return acc_a + jnp.where(m, g, 0).astype(acc_a.dtype)

            grads_st = jax.tree.map(mask_acc, grads_st, gp)
            # stage 0's input cotangent = this microbatch's embedding grad
            e_idx = jnp.clip(b_mb[0], 0, M - 1)
            old = jax.lax.dynamic_index_in_dim(g_emb, e_idx, 0,
                                               keepdims=False)
            g_emb = jax.lax.dynamic_update_index_in_dim(
                g_emb, jnp.where(valid_b[0], gx[0].astype(g_emb.dtype),
                                 old), e_idx, 0)
            g_next = shard(jnp.roll(gx, -1, axis=0), state_spec)
            return g_next, (grads_st, grads_other, g_emb)

        def tail_inject(t, y, g_state, acc, loss_acc):
            """Loss + dL/dh for the microbatch finishing its forward at
            this steady tick; injected as stage S-1's backward input."""
            grads_st, grads_other, g_emb = acc
            m_out = t - (S - 1)          # always valid in steady ticks
            mb_batch = mb_batch_at(m_out)
            loss_mb, tail_vjp = jax.vjp(
                lambda o, h: tail_sum(o, h, mb_batch), other, y[S - 1])
            g_o, g_h = tail_vjp(1.0 / W)
            grads_other = jax.tree.map(
                lambda a, g: a + g.astype(a.dtype), grads_other, g_o)
            g_state = g_state.at[S - 1].set(g_h.astype(g_state.dtype))
            return g_state, (grads_st, grads_other, g_emb), \
                loss_acc + loss_mb / W

        # accumulators
        grads_st0 = {n: shard(jnp.zeros(v.shape, jnp.float32), P("pp"))
                     for n, v in staged.items() if n != _VALID_KEY}
        grads_other0 = jax.tree.map(
            lambda v: jnp.zeros(v.shape, jnp.float32), other)
        g_emb0 = jnp.zeros((M, mb, S_len, D), emb.dtype)
        state0 = jnp.zeros((S, mb, S_len, D), emb.dtype)
        saved0 = jnp.zeros((S, C, mb, S_len, D), emb.dtype)
        g_state0 = jnp.zeros((S, mb, S_len, D), emb.dtype)

        def warm_body(carry, t):
            state, saved = carry
            state, saved, _ = f_phase(t, state, saved)
            return (state, saved), None

        (state, saved), _ = jax.lax.scan(
            warm_body, (state0, saved0), jnp.arange(S - 1))

        def steady_body(carry, t):
            state, saved, g_state, acc, loss_acc = carry
            state, saved, y = f_phase(t, state, saved)
            g_state, acc, loss_acc = tail_inject(t, y, g_state, acc,
                                                 loss_acc)
            g_state, acc = b_phase(t, saved, g_state, acc)
            return (state, saved, g_state, acc, loss_acc), None

        acc = (grads_st0, grads_other0, g_emb0)
        carry = (state, saved, g_state0, acc, jnp.zeros((), jnp.float32))
        carry, _ = jax.lax.scan(steady_body, carry,
                                jnp.arange(S - 1, M + S - 1))
        _, saved, g_state, acc, loss = carry

        def drain_body(carry, t):
            saved, g_state, acc = carry
            g_state, acc = b_phase(t, saved, g_state, acc)
            return (saved, g_state, acc), None

        (_, _, acc), _ = jax.lax.scan(
            drain_body, (saved, g_state, acc),
            jnp.arange(M + S - 1, M + 2 * (S - 1)))
        grads_st, grads_other, g_emb = acc

        grads = self._pipeline_epilogue(
            ctx, batch, grads_st, grads_other, g_emb,
            unstage=lambda v: v.reshape((-1,) + v.shape[2:]))
        return loss, grads

    # -- interleaved 1F1B (virtual pipeline, VPP) --------------------------
    def _stage_view_vpp(self, stacked, S, v):
        """(L, ...) -> (S, v, k, ...): device s holds local chunks l =
        global stages l*S+s; stage dim sharded over 'pp', each device's v
        chunks fully local (the per-tick chunk gather is a local
        dynamic-slice, no cross-device traffic)."""
        k = self._num_layers // (S * v)
        out = {}
        for n, val in stacked.items():
            r = val.reshape((v, S, k) + val.shape[1:]).swapaxes(0, 1)
            out[n] = jax.lax.with_sharding_constraint(
                r, NamedSharding(self.mesh, P("pp")))
        return out

    def _gather_chunks(self, staged, idx):
        """Per-stage local chunk select: staged (S, v, k, ...) + idx (S,)
        -> (S, k, ...)."""
        pick = jax.vmap(
            lambda p, i: jax.lax.dynamic_index_in_dim(p, i, 0,
                                                      keepdims=False))
        return {n: pick(val, idx) for n, val in staged.items()}

    def _pipeline_vpp_grads(self, params_c, batch):
        """Interleaved-1F1B (virtual pipeline) compiled schedule
        (reference: pipeline_parallel.py:906 PipelineParallelWithInterleave
        — Megatron chunk-level warmup order). Same lockstep-ring machinery
        as `_pipeline_1f1b_grads`, but each device holds v layer chunks and
        the per-tick chunk/microbatch/slot choices come from the
        `build_interleaved_schedule` tick tables (scanned over as xs).
        Shrinks the pipeline bubble from 2(S-1) full-stage ops to
        ~(v+1)S chunk ops — the v-fold reduction of the interleave paper —
        at the cost of a deeper saved-activation buffer ((v+1)S-1 slots vs
        min(M, 2S-1))."""
        mesh = self.mesh
        S = mesh.shape["pp"]
        v = self.config.interleave
        M = self.config.num_microbatches
        L = self._num_layers
        if L % (S * v) != 0:
            raise ValueError(
                f"{L} layers not divisible by pp*interleave={S * v}")

        tab, T, warm_end, steady_end, C = build_interleaved_schedule(
            S, v, M)

        ctx = self._pipeline_common(params_c, batch)
        other, tail_sum = ctx.other, ctx.tail_sum
        emb, mb, S_len, D = ctx.emb, ctx.mb, ctx.S_len, ctx.D
        x_mb, mb_batch_at, W = ctx.x_mb, ctx.mb_batch_at, ctx.W
        state_spec, saved_spec, shard = (ctx.state_spec, ctx.saved_spec,
                                         ctx.shard)
        staged = self._stage_view_vpp(ctx.stacked, S, v)

        def f_phase(row, state, saved):
            inject = jax.lax.dynamic_index_in_dim(
                x_mb, jnp.clip(row["inject_m"], 0, M - 1), 0,
                keepdims=False)
            state = state.at[0].set(
                jnp.where(row["inject_valid"], inject, state[0]))
            state = shard(state, state_spec)

            def save_one(saved_s, h_s, slot, ok):
                old = jax.lax.dynamic_index_in_dim(saved_s, slot, 0,
                                                   keepdims=False)
                return jax.lax.dynamic_update_index_in_dim(
                    saved_s, jnp.where(ok, h_s, old), slot, 0)

            saved = jax.vmap(save_one)(saved, state, row["f_slot"],
                                       row["f_valid"])
            saved = shard(saved, saved_spec)
            ch = self._gather_chunks(staged, row["f_l"])
            y = jax.vmap(self._stage_fwd)(ch, state)
            y = shard(y, state_spec)
            return jnp.roll(y, 1, axis=0), saved, y

        def b_phase(row, saved, g_in, acc):
            grads_st, grads_other, g_emb = acc

            def get_one(saved_s, slot):
                return jax.lax.dynamic_index_in_dim(saved_s, slot, 0,
                                                    keepdims=False)

            h_saved = jax.vmap(get_one)(saved, row["b_slot"])
            ch = self._gather_chunks(staged, row["b_l"])

            def one_bwd(stage_params, h_in, g):
                _, vjp = jax.vjp(self._stage_fwd, stage_params, h_in)
                gp, gx = vjp(g)
                return gp, gx

            gp, gx = jax.vmap(one_bwd)(ch, h_saved, g_in)
            valid = row["b_valid"]

            def scatter_acc(acc_a, g):
                # acc_a (S, v, k, ...), g (S, k, ...): add into each
                # stage's chunk row b_l[s], masked by validity
                def one(a_s, g_s, li, ok):
                    cur = jax.lax.dynamic_index_in_dim(a_s, li, 0,
                                                       keepdims=False)
                    upd = cur + jnp.where(ok, g_s, 0).astype(a_s.dtype)
                    return jax.lax.dynamic_update_index_in_dim(
                        a_s, upd, li, 0)

                return jax.vmap(one)(acc_a, g, row["b_l"], valid)

            grads_st = {n: scatter_acc(grads_st[n], gp[n])
                        for n in grads_st}
            e_idx = jnp.clip(row["emb_m"], 0, M - 1)
            old = jax.lax.dynamic_index_in_dim(g_emb, e_idx, 0,
                                               keepdims=False)
            g_emb = jax.lax.dynamic_update_index_in_dim(
                g_emb, jnp.where(row["emb_valid"],
                                 gx[0].astype(g_emb.dtype), old), e_idx, 0)
            g_next = shard(jnp.roll(gx, -1, axis=0), state_spec)
            return g_next, (grads_st, grads_other, g_emb)

        def tail_inject(row, y, g_state, acc, loss_acc):
            """Loss + dL/dh for a microbatch finishing its LAST chunk at
            stage S-1 this tick. Under lax.cond on the (replicated)
            per-tick validity scalar so non-tail steady ticks skip the
            lm_head/CE compute entirely (~(v-1)/v of steady ticks)."""
            grads_st, grads_other, g_emb = acc

            def true_fn(ops):
                y_last, g_state_, grads_other_, loss_ = ops
                mb_batch = mb_batch_at(jnp.clip(row["tail_m"], 0, M - 1))
                loss_mb, tail_vjp = jax.vjp(
                    lambda o, h: tail_sum(o, h, mb_batch), other, y_last)
                g_o, g_h = tail_vjp(1.0 / W)
                grads_other_ = jax.tree.map(
                    lambda a, g: a + g.astype(a.dtype), grads_other_, g_o)
                g_state_ = g_state_.at[S - 1].set(
                    g_h.astype(g_state_.dtype))
                return g_state_, grads_other_, loss_ + loss_mb / W

            def false_fn(ops):
                _, g_state_, grads_other_, loss_ = ops
                return g_state_, grads_other_, loss_

            g_state, grads_other, loss_acc = jax.lax.cond(
                row["tail_valid"], true_fn, false_fn,
                (y[S - 1], g_state, grads_other, loss_acc))
            return g_state, (grads_st, grads_other, g_emb), loss_acc

        # accumulators
        grads_st0 = {n: shard(jnp.zeros(val.shape, jnp.float32), P("pp"))
                     for n, val in staged.items()}
        grads_other0 = jax.tree.map(
            lambda val: jnp.zeros(val.shape, jnp.float32), other)
        g_emb0 = jnp.zeros((M, mb, S_len, D), emb.dtype)
        state0 = jnp.zeros((S, mb, S_len, D), emb.dtype)
        saved0 = jnp.zeros((S, C, mb, S_len, D), emb.dtype)
        g_state0 = jnp.zeros((S, mb, S_len, D), emb.dtype)

        rows = {n: jnp.asarray(val) for n, val in tab.items()}

        def rows_at(t0, t1):
            return {n: val[t0:t1] for n, val in rows.items()}

        def warm_body(carry, row):
            state, saved = carry
            state, saved, _ = f_phase(row, state, saved)
            return (state, saved), None

        (state, saved), _ = jax.lax.scan(
            warm_body, (state0, saved0), rows_at(0, warm_end))

        def steady_body(carry, row):
            state, saved, g_state, acc, loss_acc = carry
            state, saved, y = f_phase(row, state, saved)
            g_state, acc, loss_acc = tail_inject(row, y, g_state, acc,
                                                 loss_acc)
            g_state, acc = b_phase(row, saved, g_state, acc)
            return (state, saved, g_state, acc, loss_acc), None

        acc = (grads_st0, grads_other0, g_emb0)
        carry = (state, saved, g_state0, acc, jnp.zeros((), jnp.float32))
        carry, _ = jax.lax.scan(steady_body, carry,
                                rows_at(warm_end, steady_end))
        _, saved, g_state, acc, loss = carry

        def drain_body(carry, row):
            saved, g_state, acc = carry
            g_state, acc = b_phase(row, saved, g_state, acc)
            return (saved, g_state, acc), None

        (_, _, acc), _ = jax.lax.scan(
            drain_body, (saved, g_state, acc), rows_at(steady_end, T))
        grads_st, grads_other, g_emb = acc

        # unstage (S, v, k, ...) -> (v, S, k, ...) -> (L, ...):
        # layer (l*S+s)*k + ki
        grads = self._pipeline_epilogue(
            ctx, batch, grads_st, grads_other, g_emb,
            unstage=lambda val: val.swapaxes(0, 1).reshape(
                (L,) + val.shape[3:]))
        return loss, grads
