"""Pipeline parallelism, compiled (GPipe schedule inside one XLA program).

The reference implements PP as a Python runtime: PipelineLayer stage
partitioning + 1F1B/interleave schedulers exchanging activations over NCCL
p2p (reference: .../meta_parallel/pipeline_parallel.py:440
forward_backward_pipeline, pp_layers.py:92 SegmentLayers,
pp_utils/p2p_communication.py:313), plus an actor-based static-mode runtime
(fleet_executor Carrier/Interceptor, SURVEY.md §2.5).

TPU-native replacement (SURVEY.md §7 "hardest parts" #2): the schedule is
DATA, not control flow. The decoder stack's per-layer params are stacked
with a leading layer dim, reshaped to (stages, layers_per_stage, ...) with
the stage dim sharded over the mesh's 'pp' axis. One `lax.scan` over
pipeline ticks runs `vmap(stage_fn)` — XLA partitions the stage dim so each
pp device computes its own stage — and `jnp.roll` on the stage-sharded
buffer hands activations to the next stage as an ICI collective-permute.
Backward is just jax.grad through the scan: XLA schedules the reverse
pipeline (the 1F1B memory trick is subsumed by per-stage remat).

Bubble fraction is (S-1)/(M+S-1) like GPipe; interleaved/virtual stages
(reference PipelineParallelWithInterleave) map to circular repeats of the
same machinery and can cut it further.
"""
from __future__ import annotations

import re
from dataclasses import dataclass

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.jit.functional import functional_call, state_tensors
from paddle_tpu.parallel.plan import ShardingPlan
from paddle_tpu.parallel.trainer import Trainer, TrainStepConfig, _cast_tree

STACK_PREFIX = "pipeline.layers::"


def _layer_param_names(model):
    """Group `model.model.layers.<i>.<local>` param names by local name."""
    pat = re.compile(r"^(.*\.layers)\.(\d+)\.(.+)$")
    groups: dict[str, dict[int, str]] = {}
    base = None
    for name in state_tensors(model):
        m = pat.match(name)
        if m:
            base = m.group(1)
            groups.setdefault(m.group(3), {})[int(m.group(2))] = name
    return base, groups


class PipelinePlan(ShardingPlan):
    """Wraps a base plan: stacked layer params get 'pp' prepended on the
    layer/stage dim; everything else falls through."""

    def __init__(self, base: ShardingPlan):
        self.base = base
        self.rules = base.rules
        self.default = base.default

    def spec_for(self, name: str, ndim: int | None = None) -> P:
        if name.startswith(STACK_PREFIX):
            local = name[len(STACK_PREFIX):]
            sub = self.base.spec_for(local)
            return P("pp", *tuple(sub))
        return self.base.spec_for(name)


@dataclass
class PipelineConfig(TrainStepConfig):
    num_microbatches: int = 4


class PipelineTrainer(Trainer):
    """Trainer whose decoder stack runs under the compiled GPipe schedule.

    Assumes the model has `model.model.layers` (a list of identical
    decoder layers, e.g. LlamaForCausalLM), an embedding + final norm +
    head reachable through the remaining params — which is exactly the
    split PipelineLayer's SegmentLayers computes for the reference.
    """

    def __init__(self, model, optimizer, mesh, plan,
                 config: PipelineConfig | None = None):
        self._tpl_layer = model.model.layers[0]
        base_names, groups = _layer_param_names(model)
        self._layers_base = base_names
        self._layer_groups = groups
        self._num_layers = len(model.model.layers)
        cfg = config or PipelineConfig()
        super().__init__(model, optimizer, mesh=mesh,
                         plan=PipelinePlan(plan), config=cfg)

    # -- stacked state ----------------------------------------------------
    def _init_state(self):
        tensors = state_tensors(self.model)
        stacked = {}
        consumed = set()
        for local, by_idx in self._layer_groups.items():
            names = [by_idx[i] for i in range(self._num_layers)]
            stacked[STACK_PREFIX + local] = jnp.stack(
                [tensors[n]._value for n in names])
            consumed.update(names)
        self.params = {n: t._value for n, t in tensors.items()
                       if n not in consumed}
        self.params.update(stacked)
        trainable = {n for n, t in tensors.items() if not t.stop_gradient}
        self.param_names = [n for n in self.params
                            if n.startswith(STACK_PREFIX)
                            or n in trainable]
        self.opt_state = self.optimizer.init_state_arrays(
            {n: self.params[n] for n in self.param_names})
        if self.mesh is not None and self.plan is not None:
            self._shard_state()

    def sync_to_model(self):
        tensors = state_tensors(self.model)
        for n, arr in self.params.items():
            if n.startswith(STACK_PREFIX):
                local = n[len(STACK_PREFIX):]
                for i, name in sorted(
                        self._layer_groups[local].items()):
                    tensors[name]._value = arr[i]
            else:
                tensors[n]._value = arr
        return self.model

    # -- pipelined loss ----------------------------------------------------
    def _layer_apply(self, layer_params: dict, h):
        """One decoder layer, functional (template-layer swap)."""
        out = functional_call(self._tpl_layer, layer_params,
                              Tensor(h, stop_gradient=False))
        return out._value if isinstance(out, Tensor) else out

    def _loss_from_batch(self, params_c, batch):
        cfg_m = self.model.config
        mesh = self.mesh
        n_pp = mesh.shape["pp"]
        M = self.config.num_microbatches
        L = self._num_layers
        assert L % n_pp == 0, f"{L} layers not divisible by pp={n_pp}"
        k = L // n_pp

        input_ids = batch["input_ids"]
        labels = batch.get("labels")
        B = input_ids.shape[0]
        assert B % M == 0, f"batch {B} not divisible by microbatches {M}"

        other = {n: v for n, v in params_c.items()
                 if not n.startswith(STACK_PREFIX)}
        stacked = {n[len(STACK_PREFIX):]: v
                   for n, v in params_c.items()
                   if n.startswith(STACK_PREFIX)}
        # (L, ...) -> (S, k, ...), stage dim sharded over 'pp'
        staged = {
            n: jax.lax.with_sharding_constraint(
                v.reshape((n_pp, k) + v.shape[1:]),
                NamedSharding(mesh, P("pp")))
            for n, v in stacked.items()}

        # embedding (cheap; ordinary GSPMD)
        emb = functional_call(
            self.model.model.embed_tokens,
            {"weight": other[
                f"{self._embed_prefix()}.weight"]},
            Tensor(input_ids, stop_gradient=True))._value
        D = emb.shape[-1]
        S_len = emb.shape[1]
        mb = B // M
        x_mb = emb.reshape(M, mb, S_len, D)

        dp_axes = tuple(a for a in ("dp", "fsdp") if a in mesh.axis_names)
        state_spec = P("pp", dp_axes if dp_axes else None)

        def stage_fn(stage_params, h):
            def body(hh, one_layer):
                return self._layer_apply(one_layer, hh), None
            out, _ = jax.lax.scan(body, h, stage_params)
            return out

        stage_fn = jax.checkpoint(stage_fn)

        def tick(carry, t):
            state, outputs = carry
            inject = jax.lax.dynamic_index_in_dim(
                x_mb, jnp.clip(t, 0, M - 1), axis=0, keepdims=False)
            state = state.at[0].set(
                jnp.where(t < M, inject, state[0]))
            state = jax.lax.with_sharding_constraint(
                state, NamedSharding(mesh, state_spec))
            y = jax.vmap(stage_fn)(staged_stacked, state)
            y = jax.lax.with_sharding_constraint(
                y, NamedSharding(mesh, state_spec))
            out_t = y[-1]
            oidx = jnp.clip(t - (n_pp - 1), 0, M - 1)
            outputs = jax.lax.dynamic_update_index_in_dim(
                outputs,
                jnp.where(t >= n_pp - 1,
                          out_t,
                          jax.lax.dynamic_index_in_dim(
                              outputs, oidx, 0, keepdims=False)),
                oidx, 0)
            state = jnp.roll(y, 1, axis=0)
            return (state, outputs), None

        staged_stacked = staged
        T = M + n_pp - 1
        state0 = jnp.zeros((n_pp, mb, S_len, D), emb.dtype)
        outputs0 = jnp.zeros((M, mb, S_len, D), emb.dtype)
        (_, outputs), _ = jax.lax.scan(
            tick, (state0, outputs0), jnp.arange(T))

        h = outputs.reshape(B, S_len, D)
        # final norm + head + shifted CE via the model's own tail
        norm_w = other[f"{self._norm_prefix()}.weight"]
        h = functional_call(self.model.model.norm, {"weight": norm_w},
                            Tensor(h, stop_gradient=False))._value
        logits = self._head_logits(other, h)
        if labels is None:
            return jnp.zeros((), jnp.float32)
        # shift the labels, not the logits: slicing logits[:, :-1] copies
        # the (B*S, vocab) tensor (see models/llama.py next_token_loss).
        # Final position and user -100 padding are masked out of the mean.
        lf = logits.astype(jnp.float32)
        shifted = jnp.concatenate(
            [labels[:, 1:],
             jnp.full((labels.shape[0], 1), -100, labels.dtype)], axis=1)
        keep = shifted != -100
        logz = jax.nn.logsumexp(lf, axis=-1)
        tgt = jnp.take_along_axis(
            lf, jnp.where(keep, shifted, 0)[..., None].astype(jnp.int32),
            axis=-1)[..., 0]
        per = (logz - tgt) * keep
        return (per.sum()
                / jnp.maximum(keep.sum(), 1)).astype(jnp.float32)

    def _embed_prefix(self):
        for n in self.params:
            if n.endswith("embed_tokens.weight"):
                return n[: -len(".weight")]
        raise KeyError("embed_tokens.weight not found")

    def _norm_prefix(self):
        cands = [n for n in self.params
                 if n.endswith(".norm.weight")
                 and not n.startswith(STACK_PREFIX)]
        return cands[0][: -len(".weight")]

    def _head_logits(self, other, h):
        name = next((n for n in other if n.endswith("lm_head.weight")),
                    None)
        if name is not None:
            return jnp.einsum("bsd,dv->bsv", h, other[name])
        w = other[f"{self._embed_prefix()}.weight"]
        return jnp.einsum("bsd,vd->bsv", h, w)
