"""paddle_tpu.jit (reference: python/paddle/jit/__init__.py).

to_static -> jax.jit tracing (jit/api.py); save/load -> StableHLO export
(replacing the reference's translated_layer.py + paddle/fluid/jit/ C++
deployment engine — a serialized StableHLO module is directly loadable by
any XLA runtime, which is the TPU-native deployment story, SURVEY.md §2.7
"Inference engine").
"""
from __future__ import annotations

import os
import pickle

import jax
import numpy as np

from paddle_tpu.jit.api import (to_static, not_to_static, StaticFunction,
                                InputSpec, enable_to_static, ignore_module,
                                explain, compilation_cache_stats)
from paddle_tpu.jit.functional import functional_call, state_arrays, state_tensors
from paddle_tpu.jit.dy2static import (cond, while_loop, scan,
                                      Dy2StaticTransformError)
from paddle_tpu.jit import dy2static
from paddle_tpu.core.tensor import Tensor


def save(layer, path, input_spec=None, **configs):
    """Export a Layer (or StaticFunction) for deployment.

    Produces `path.pdmodel` (serialized StableHLO via jax.export) and
    `path.pdiparams` (state dict pickle) — same two-artifact layout as the
    reference (reference: python/paddle/jit/api.py save), different format.
    """
    from paddle_tpu.nn.layer.layers import Layer

    if input_spec is None:
        raise ValueError("jit.save requires input_spec on paddle_tpu")
    specs = [s if isinstance(s, InputSpec) else InputSpec(s.shape, s.dtype)
             for s in input_spec]

    fn = layer.forward if isinstance(layer, Layer) else layer
    target = layer if isinstance(layer, Layer) else None
    state = state_arrays(target) if target is not None else {}

    def pure(state_, *xs):
        ts = [Tensor(x) for x in xs]
        if target is not None:
            from paddle_tpu.jit.functional import _swapped
            from paddle_tpu.core.tape import no_grad
            with no_grad(), _swapped(target, state_):
                out = target.forward(*ts) if not isinstance(fn, StaticFunction) \
                    else fn._fn(*ts)
        else:
            out = fn(*ts)
        return jax.tree.map(
            lambda t: t._value if isinstance(t, Tensor) else t, out,
            is_leaf=lambda t: isinstance(t, Tensor))

    from paddle_tpu.core.dtype import convert_dtype
    shaped = [jax.ShapeDtypeStruct(
        tuple(d if d != -1 else 1 for d in s.shape),
        convert_dtype(s.dtype)) for s in specs]
    exported = jax.export.export(jax.jit(pure))(state, *shaped)

    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path + ".pdmodel", "wb") as f:
        f.write(exported.serialize())
    from paddle_tpu.framework.io_utils import save as _save
    if target is not None:
        _save(target.state_dict(), path + ".pdiparams")


class TranslatedLayer:
    """Loaded deployable program (reference: translated_layer.py)."""

    def __init__(self, exported, state):
        self._exported = exported
        self._state = state

    def __call__(self, *args):
        arrays = [a._value if isinstance(a, Tensor) else np.asarray(a)
                  for a in args]
        out = self._exported.call(self._state, *arrays)
        return jax.tree.map(Tensor, out)

    def forward(self, *args):
        return self(*args)


def load(path, **configs):
    with open(path + ".pdmodel", "rb") as f:
        exported = jax.export.deserialize(f.read())
    state = {}
    if os.path.exists(path + ".pdiparams"):
        from paddle_tpu.framework.io_utils import load as _load
        sd = _load(path + ".pdiparams")
        state = {k: v._value if isinstance(v, Tensor) else np.asarray(v)
                 for k, v in sd.items()}
    return TranslatedLayer(exported, state)


def set_verbosity(level=0, also_to_stdout=False):
    """(reference: jit/dy2static/logging_utils.py set_verbosity). Recorded
    for parity: jit tracing emits jaxprs, not transformed source, so there
    is no transform log to verbose-print; the flag is queryable via
    paddle.get_flags."""
    from paddle_tpu.core import flags
    flags.set_flags({"FLAGS_jit_verbosity": int(level)})


def set_code_level(level=100, also_to_stdout=False):
    """(reference: logging_utils.py set_code_level). Tracing produces
    jaxprs, not transformed source; the level is recorded for parity."""
    from paddle_tpu.core import flags
    flags.set_flags({"FLAGS_jit_code_level": int(level)})
