"""Functional bridge: stateful Layers <-> pure functions.

This is the load-bearing piece of the TPU design (SURVEY.md §3.3): the
reference needs an AST/bytecode translator (SOT, reference:
python/paddle/jit/sot/ + paddle/fluid/pybind/eval_frame.c:127) to capture
imperative programs into its IR. Here capture is jax tracing; the only
machinery needed is swapping a Layer's Parameters for traced values for the
duration of the trace — ~60 lines instead of a symbolic bytecode interpreter.

`functional_call(layer, state, *args)` runs layer.forward with parameters
and buffers taken from `state` (a flat dict name -> array), recording
nothing on the eager tape. It is the foundation of to_static, of the jitted
train step, and of every parallel transform (shard_map/pjit see only pure
functions).
"""
from __future__ import annotations

import contextlib
from typing import Any

import jax

# NOTE: for shard_map over functional_call, import it from
# paddle_tpu.core.jax_compat — the bare jax spellings are
# version-fragile (tools/check_jax_compat.py enforces this)
from paddle_tpu.core.tape import no_grad, push_tape, pop_tape
from paddle_tpu.core.tensor import Tensor


def state_tensors(layer) -> dict[str, Tensor]:
    """Flat dict of all parameters and buffers, keyed by qualified name."""
    out = dict(layer.named_parameters())
    for name, buf in layer.named_buffers():
        out[name] = buf
    return out


def state_arrays(layer) -> dict[str, jax.Array]:
    return {k: t._value for k, t in state_tensors(layer).items()}


@contextlib.contextmanager
def _swapped(layer, arrays: dict[str, Any]):
    tensors = state_tensors(layer)
    saved = {}
    try:
        for name, arr in arrays.items():
            t = tensors[name]
            saved[name] = t._value
            t._value = arr
        yield
    finally:
        for name, arr in saved.items():
            tensors[name]._value = arr


def functional_call(layer, state: dict[str, Any], *args, **kwargs):
    """Pure-functional forward: returns raw outputs with `state` in place of
    the layer's own parameter values. Safe under jax tracing."""
    prev = push_tape()
    try:
        with no_grad(), _swapped(layer, state):
            return layer(*args, **kwargs)
    finally:
        pop_tape(prev)
