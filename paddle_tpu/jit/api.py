"""@to_static: compile imperative code into one XLA program.

TPU-native replacement for the reference's entire dy2static stack
(reference: python/paddle/jit/api.py:171 to_static; the SOT bytecode tracer
sot/opcode_translator/executor/opcode_executor.py:303 with its CPython
frame-eval hook pybind/eval_frame.c:38; the AST transpiler
dy2static/program_translator.py:325; PIR program construction and the
PirInterpreter). Per SURVEY.md §3.3 all of that collapses to `jax.jit`
tracing: guards == jit's shape/dtype cache keys, graph breaks don't exist
(tracing is complete), and the executor is XLA.

Autograd contract: calling a StaticFunction in a grad-enabled context
records the WHOLE traced program as a single tape op whose vjp is the
XLA-compiled backward (jax.vjp of the pure function). loss.backward()
through a to_static model is therefore one fused forward + one fused
backward executable — the reference's interpreter replays op-by-op instead.
"""
from __future__ import annotations

import functools
import threading

import numpy as np
import jax

from paddle_tpu.core.tape import no_grad, push_tape, pop_tape
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.jit.functional import _swapped, state_tensors

_tracing = threading.local()

# live StaticFunctions, for process-wide cache stats (weak: the registry
# must not keep a model's compiled steps alive)
import weakref                                              # noqa: E402

_all_static_functions: "weakref.WeakSet" = weakref.WeakSet()


def _in_tracing() -> bool:
    return getattr(_tracing, "depth", 0) > 0


class InputSpec:
    """Shape/dtype declaration (reference: python/paddle/static/input_spec.py).
    Dims of -1 ("dynamic") are accepted; jit simply retraces per concrete
    shape (XLA wants static shapes — SURVEY.md §7 design stance)."""

    def __init__(self, shape, dtype="float32", name=None,
                 stop_gradient=False):
        self.shape = tuple(shape)
        self.dtype = dtype
        self.name = name
        self.stop_gradient = stop_gradient

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype})"


def _find_layer(fn):
    from paddle_tpu.nn.layer.layers import Layer
    if isinstance(fn, Layer):
        return fn, fn.forward
    owner = getattr(fn, "__self__", None)
    if owner is not None and isinstance(owner, Layer):
        return owner, fn
    return None, fn


def _isdiff(dtype):
    import jax.numpy as jnp
    return jnp.issubdtype(dtype, jnp.inexact)


def _is_arr(x):
    return isinstance(x, (jax.Array, np.ndarray))


class StaticFunction:
    """The compiled callable returned by @to_static."""

    def __init__(self, fn, input_spec=None, build_strategy=None,
                 backend=None, full_graph=True):
        self._layer, self._fn = _find_layer(fn)
        self._input_spec = input_spec
        self._jit_cache = {}
        self._out_treedefs = {}
        self._traced_fn = None      # set lazily (AST control-flow rewrite)
        self._fell_back = False
        # guard/retrace observability (reference: SOT guards,
        # sot/opcode_translator/executor/guards.py — "why did my jit
        # recompile?"): every call's guard signature is checked against
        # the seen set; a novel one is a (re)trace event whose CAUSE
        # (which input's shape/dtype/treedef/static value changed) is
        # recorded in _retrace_log. jit.explain(fn) renders it.
        self._seen_sigs = set()
        self._last_sig = None
        self._retrace_log = []
        self._call_count = 0
        functools.update_wrapper(self, self._fn)
        _all_static_functions.add(self)

    def _body_fn(self):
        """The function actually traced: the dy2static AST rewrite of
        self._fn when it contains if/while (so Tensor predicates lower to
        lax.cond/while_loop), else self._fn itself."""
        if self._traced_fn is None:
            import warnings
            from paddle_tpu.jit.dy2static import (ast_transform,
                                                  Dy2StaticTransformError)
            raw = getattr(self._fn, "__func__", self._fn)
            try:
                new = ast_transform(raw)
            except Dy2StaticTransformError as e:
                warnings.warn(
                    f"to_static: control-flow rewrite of "
                    f"{getattr(raw, '__qualname__', raw)} failed ({e}); "
                    "tracing the original body (Tensor-predicate "
                    "if/while will fall back to eager execution)")
                new = None
            if new is not None and self._fn is not raw:
                # rebind: transformed plain function <- bound method
                layer = self._fn.__self__
                new = functools.partial(new, layer)
            self._traced_fn = new or self._fn
        return self._traced_fn

    # ---- tracing body ----------------------------------------------------
    def _run_traced(self, state, dyn_arrays, key):
        """Body executed under jax.jit: rebuild Tensor args, run the python
        function, return flat output arrays."""
        treedef, static_leaves, dyn_idx, sg_flags = key
        leaves = dict(static_leaves)
        for i, a in zip(dyn_idx, dyn_arrays):
            leaves[i] = a
        sg = dict(sg_flags)
        ordered = []
        for i in sorted(leaves):
            l = leaves[i]
            if _is_arr(l) or hasattr(l, "aval"):
                ordered.append(Tensor(l, stop_gradient=sg.get(i, True)))
            else:
                ordered.append(l)
        args, kwargs = jax.tree.unflatten(treedef, ordered)

        fn = self._body_fn()
        _tracing.depth = getattr(_tracing, "depth", 0) + 1
        prev = push_tape()
        try:
            with no_grad():
                if self._layer is not None:
                    with _swapped(self._layer, state):
                        out = fn(*args, **kwargs)
                else:
                    out = fn(*args, **kwargs)
        finally:
            pop_tape(prev)
            _tracing.depth -= 1

        flat, out_treedef = jax.tree.flatten(
            out, is_leaf=lambda x: isinstance(x, Tensor))
        arrays = [f._value if isinstance(f, Tensor) else f for f in flat]
        self._out_treedefs[key] = out_treedef
        return tuple(arrays)

    def _get_jitted(self, key):
        if key not in self._jit_cache:
            self._jit_cache[key] = jax.jit(
                lambda state, dyn: self._run_traced(state, dyn, key))
        return self._jit_cache[key]

    # ---- call ------------------------------------------------------------
    def __call__(self, *args, **kwargs):
        from paddle_tpu.core.tape import grad_enabled, TapeNode, current_tape

        leaves, treedef = jax.tree.flatten(
            (args, kwargs), is_leaf=lambda x: isinstance(x, Tensor))
        arr_leaves = []
        sg_flags = []
        for i, l in enumerate(leaves):
            if isinstance(l, Tensor):
                arr_leaves.append(l._value)
                sg_flags.append((i, l.stop_gradient))
            else:
                arr_leaves.append(l)

        dyn_idx = tuple(i for i, a in enumerate(arr_leaves) if _is_arr(a))
        static_leaves = tuple((i, a) for i, a in enumerate(arr_leaves)
                              if i not in set(dyn_idx))
        key = (treedef, static_leaves, dyn_idx, tuple(sg_flags))
        self._call_count += 1
        sig = (key, tuple((tuple(arr_leaves[i].shape),
                           str(arr_leaves[i].dtype)) for i in dyn_idx))
        if sig not in self._seen_sigs:
            self._record_retrace(sig, args, kwargs)
            self._seen_sigs.add(sig)
        # track EVERY call's signature: a retrace cause must name the
        # transition from the PREVIOUS CALL the user made, not from the
        # last novel trace (code-review r4)
        self._last_sig = sig
        jitted = self._get_jitted(key)
        dyn_vals = [arr_leaves[i] for i in dyn_idx]

        state_t = state_tensors(self._layer) if self._layer is not None else {}
        state = {k: t._value for k, t in state_t.items()}

        # which inputs require grad
        tensor_by_leaf = {i: l for i, l in enumerate(leaves)
                          if isinstance(l, Tensor)}
        diff_dyn_pos = [p for p, i in enumerate(dyn_idx)
                        if i in tensor_by_leaf
                        and not tensor_by_leaf[i].stop_gradient
                        and _isdiff(arr_leaves[i].dtype)]
        diff_in = [tensor_by_leaf[dyn_idx[p]] for p in diff_dyn_pos]
        diff_names = [k for k, t in state_t.items()
                      if not t.stop_gradient and _isdiff(t._value.dtype)]
        need_grad = grad_enabled() and (diff_in or diff_names)

        if not need_grad:
            try:
                out_arrays = jitted(state, dyn_vals)
            except (TypeError, UnboundLocalError,
                    jax.errors.ConcretizationTypeError,
                    jax.errors.TracerArrayConversionError) as e:
                return self._graph_break(e, args, kwargs)
            return self._unflatten_out(key, out_arrays)

        def g(diff_state, diff_arrs):
            full_state = dict(state)
            full_state.update(diff_state)
            dv = list(dyn_vals)
            for p, a in zip(diff_dyn_pos, diff_arrs):
                dv[p] = a
            return jitted(full_state, dv)

        try:
            out_arrays, vjp_fn = jax.vjp(
                g, {k: state[k] for k in diff_names},
                [t._value for t in diff_in])
        except (TypeError, UnboundLocalError,
                jax.errors.ConcretizationTypeError,
                jax.errors.TracerArrayConversionError) as e:
            return self._graph_break(e, args, kwargs)

        out = self._unflatten_out(key, out_arrays, stop_gradient=False)
        out_tensors = [o for o in jax.tree.leaves(
            out, is_leaf=lambda x: isinstance(x, Tensor))
            if isinstance(o, Tensor)]

        def tape_vjp(cotangents):
            gs, gi = vjp_fn(tuple(cotangents))
            return [gs[k] for k in diff_names] + list(gi)

        node = TapeNode(
            "to_static",
            inputs=[state_t[k] for k in diff_names] + diff_in,
            outputs=out_tensors, vjp_fn=tape_vjp,
            out_avals=[(a.shape, a.dtype) for a in out_arrays])
        current_tape().record(node)
        return out

    # ---- guard/retrace observability ------------------------------------
    def _leaf_labels(self, args, kwargs):
        """Human-readable path per flattened (args, kwargs) leaf."""
        from jax.tree_util import tree_flatten_with_path, keystr
        paths, _ = tree_flatten_with_path(
            (args, kwargs), is_leaf=lambda x: isinstance(x, Tensor))
        return [keystr(p) for p, _leaf in paths]

    def _record_retrace(self, sig, args, kwargs):
        """Classify WHY this call needs a new trace: which guard moved
        (the reference surfaces this through SOT guard failures,
        sot/.../guards.py; here the guards are explicit tuples)."""
        prev = self._last_sig
        event = {"call": self._call_count, "kind": "first_trace",
                 "detail": "initial compilation"}
        if prev is not None:
            (ptree, pstatic, pdyn_idx, psg), pavals = prev
            (ntree, nstatic, ndyn_idx, nsg), navals = sig
            labels = self._leaf_labels(args, kwargs)

            def label(i):
                return labels[i] if i < len(labels) else f"leaf[{i}]"

            if ptree != ntree:
                event.update(kind="treedef", detail=(
                    "input structure changed: "
                    f"{ptree} -> {ntree}"))
            elif pstatic != nstatic:
                changed = [(i, o, n) for (i, o), (j, n)
                           in zip(pstatic, nstatic) if o != n or i != j] \
                    or [(None, pstatic, nstatic)]
                i, o, n = changed[0]
                event.update(kind="static_value", detail=(
                    f"static arg {label(i) if i is not None else ''} "
                    f"changed: {o!r} -> {n!r}"))
            elif psg != nsg:
                event.update(kind="stop_gradient", detail=(
                    f"stop_gradient flags changed: {psg} -> {nsg}"))
            elif pdyn_idx != ndyn_idx:
                event.update(kind="treedef", detail=(
                    f"tensor-leaf positions changed: {pdyn_idx} -> "
                    f"{ndyn_idx}"))
            else:
                for pos, (pa, na) in enumerate(zip(pavals, navals)):
                    if pa == na:
                        continue
                    kind = "dtype" if pa[0] == na[0] else "shape"
                    event.update(kind=kind, detail=(
                        f"arg {label(ndyn_idx[pos])}: "
                        f"{pa[0]}/{pa[1]} -> {na[0]}/{na[1]}"))
                    break
        self._retrace_log.append(event)

    def stats(self):
        """Compilation-cache statistics for this function (reference:
        the SOT guard/cache introspection surface)."""
        return {"name": getattr(self._fn, "__qualname__", str(self._fn)),
                "calls": self._call_count,
                "traces": len(self._retrace_log),
                "cache_entries": len(self._seen_sigs),
                "fell_back": self._fell_back,
                "retraces": list(self._retrace_log)}

    @property
    def retrace_log(self):
        return list(self._retrace_log)

    def _graph_break(self, err, args, kwargs):
        """Whole-function fallback to eager when tracing hits host-side
        data dependence the rewrite couldn't capture (the coarse
        equivalent of SOT's per-op graph break, reference
        opcode_executor.py:303 BreakGraphError)."""
        if not self._fell_back:
            import warnings
            warnings.warn(
                f"to_static: {getattr(self._fn, '__qualname__', self._fn)}"
                f" could not be traced into one program ({err}); falling "
                "back to EAGER execution. Restructure with "
                "paddle_tpu.jit.cond/while_loop to recover compilation.")
            self._fell_back = True
        return self._fn(*args, **kwargs)

    def _unflatten_out(self, key, out_arrays, stop_gradient=True):
        td = self._out_treedefs.get(key)
        wrapped = [Tensor(a, stop_gradient=stop_gradient)
                   if _is_arr(a) else a for a in out_arrays]
        if td is None:
            return wrapped[0] if len(wrapped) == 1 else tuple(wrapped)
        return jax.tree.unflatten(td, wrapped)

    # paddle API parity helpers
    @property
    def function(self):
        return self._fn

    def rollback(self):
        return self._fn


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, **kwargs):
    """Reference: python/paddle/jit/api.py:171."""

    def deco(fn):
        from paddle_tpu.nn.layer.layers import Layer
        if isinstance(fn, Layer):
            sf = StaticFunction(fn, input_spec=input_spec)
            fn.forward = sf
            return fn
        return StaticFunction(fn, input_spec=input_spec)

    if function is not None:
        return deco(function)
    return deco


def _resolve_static(fn):
    from paddle_tpu.nn.layer.layers import Layer
    if isinstance(fn, StaticFunction):
        return fn
    if isinstance(fn, Layer) and isinstance(fn.forward, StaticFunction):
        return fn.forward
    raise ValueError(
        f"{fn!r} is not a to_static-compiled function/Layer; wrap it "
        "with paddle_tpu.jit.to_static first")


def explain(fn) -> str:
    """Render WHY a to_static function (re)compiled: one line per trace
    event with the guard that moved (shape/dtype/treedef/static value/
    stop_gradient). The debugging surface the reference provides via
    SOT guard logs (sot/opcode_translator/executor/guards.py); here the
    guards are explicit, so the report is exact.

    >>> print(paddle_tpu.jit.explain(model))    # doctest: +SKIP
    """
    sf = _resolve_static(fn)
    st = sf.stats()
    lines = [f"to_static {st['name']}: {st['calls']} calls, "
             f"{st['traces']} traces, {st['cache_entries']} cache "
             f"entries" + (", FELL BACK TO EAGER" if st["fell_back"]
                           else "")]
    for i, ev in enumerate(st["retraces"]):
        lines.append(f"  trace #{i + 1} (call {ev['call']}): "
                     f"[{ev['kind']}] {ev['detail']}")
    return "\n".join(lines)


def compilation_cache_stats():
    """Process-wide compilation-cache statistics over every live
    StaticFunction: total compiled entries, traces, calls, and the
    per-function breakdown (reference: the executor cache the reference
    exposes through FLAGS + executor_statistics.cc)."""
    per_fn = [sf.stats() for sf in list(_all_static_functions)]
    return {
        "functions": len(per_fn),
        "total_calls": sum(s["calls"] for s in per_fn),
        "total_traces": sum(s["traces"] for s in per_fn),
        "total_cache_entries": sum(s["cache_entries"] for s in per_fn),
        "per_function": per_fn,
    }


def not_to_static(func=None):
    if func is None:            # @not_to_static() factory form
        return not_to_static
    func._not_to_static = True
    return func


def ignore_module(modules):
    return None


def enable_to_static(enable_to_static_bool=True):
    return None
