"""Data-dependent control flow under @to_static.

Reference: the dy2static AST transpiler (python/paddle/jit/dy2static/
program_translator.py:325, transformers/ifelse_transformer.py,
while_loop_transformer.py) rewrites `if`/`while` on Tensor predicates
into `paddle.static.nn.cond/while_loop` calls via runtime-dispatch
wrappers (convert_ifelse / convert_while); the SOT path (jit/sot/
opcode_translator/executor/opcode_executor.py:303) does the same at
bytecode level with graph-break fallback.

TPU-native version: the same source-to-source rewrite, but the target is
`lax.cond` / `lax.while_loop` so the branch/loop lands INSIDE the traced
XLA program. The dispatch is at runtime — a python-bool predicate keeps
plain python control flow (and stays unrolled under tracing, exactly like
before); a Tensor predicate routes to the lax primitive. If the rewrite
or the lax lowering fails, @to_static "graph-breaks" COARSELY: the whole
function falls back to eager execution with a one-time warning (the SOT
equivalent breaks at the offending op; one-program-or-eager is the
compiled-framework tradeoff, SURVEY.md §3.3).

Transform contract (checked at transform time, clear errors otherwise):
- `if` on a Tensor predicate: both branches may assign locals; a branch
  that `return`s requires the other branch (or the code after) to return
  too. Assigned-in-one-branch names must already exist before the `if`.
- `while` on a Tensor predicate: the loop carry is every local assigned
  in the body; shapes/dtypes must be loop-invariant (lax.while_loop).
- `for` loops are left untouched (they unroll statically under tracing;
  use paddle_tpu.jit.scan for long rolled loops).
"""
from __future__ import annotations

import ast
import functools
import inspect
import textwrap
import warnings
import weakref

import jax
import jax.numpy as jnp

from paddle_tpu.core.tensor import Tensor

__all__ = ["cond", "while_loop", "scan", "convert_ifelse", "convert_while",
           "ast_transform", "Dy2StaticTransformError"]


class Dy2StaticTransformError(Exception):
    pass


def _unwrap(x):
    return x._value if isinstance(x, Tensor) else x


def _wrap_like(arrays, template):
    out = []
    for a, t in zip(arrays, template):
        if isinstance(t, Tensor):
            out.append(Tensor(a, stop_gradient=t.stop_gradient))
        else:
            out.append(a)
    return out


def _is_tensor_pred(pred):
    return isinstance(pred, Tensor) or isinstance(pred, jax.Array) \
        or isinstance(pred, jax.core.Tracer)


# ---------------------------------------------------------------------------
# public control-flow ops (paddle.static.nn.cond / while_loop parity)
# ---------------------------------------------------------------------------

def cond(pred, true_fn, false_fn, *operands):
    """lax.cond over Tensor-valued branch functions (reference:
    python/paddle/static/nn/control_flow.py cond). Both branches must
    return matching structures of equal shapes/dtypes."""
    pv = _unwrap(pred)
    arrs = [_unwrap(o) for o in operands]

    def mk(fn):
        def body(ops):
            out = fn(*_wrap_like(ops, operands)) if operands else fn()
            return jax.tree.map(_unwrap, out,
                                is_leaf=lambda x: isinstance(x, Tensor))
        return body

    out = jax.lax.cond(jnp.asarray(pv).astype(bool).reshape(()),
                       mk(true_fn), mk(false_fn), arrs)
    return jax.tree.map(lambda a: Tensor(a, stop_gradient=True)
                        if isinstance(a, (jax.Array, jax.core.Tracer))
                        else a, out)


def while_loop(cond, body, loop_vars, is_test=False, name=None):
    """lax.while_loop over Tensor loop vars (reference:
    python/paddle/static/nn/control_flow.py while_loop — param names
    match; is_test is a static-graph hint with no meaning here).
    Carried shapes/dtypes must be loop-invariant."""
    cond_fn, body_fn = cond, body
    template = list(loop_vars)
    init = [_unwrap(v) for v in template]

    def c(carry):
        return jnp.asarray(
            _unwrap(cond_fn(*_wrap_like(carry, template)))
        ).astype(bool).reshape(())

    def b(carry):
        out = body_fn(*_wrap_like(carry, template))
        if not isinstance(out, (tuple, list)):
            out = (out,)
        return [_unwrap(o) for o in out]

    final = jax.lax.while_loop(c, b, init)
    return _wrap_like(final, template)


def scan(f, init, xs):
    """lax.scan over Tensors: f(carry, x) -> (carry, y)."""
    def body(carry, x):
        c, y = f(Tensor(carry, stop_gradient=True),
                 Tensor(x, stop_gradient=True))
        return _unwrap(c), _unwrap(y)

    carry, ys = jax.lax.scan(body, _unwrap(init), _unwrap(xs))
    return (Tensor(carry, stop_gradient=True),
            Tensor(ys, stop_gradient=True))


# ---------------------------------------------------------------------------
# runtime dispatch helpers (targets of the AST rewrite)
# ---------------------------------------------------------------------------

def convert_ifelse(pred, true_fn, false_fn, ops=()):
    """`if` rewrite target: python-bool predicates branch in python
    (staying unrolled under tracing); Tensor predicates lower to
    lax.cond. `ops` are the call-site values of the names the branches
    read (passed as parameters so python scoping cannot shadow them);
    both fns return the tuple of branch-assigned locals."""
    if not _is_tensor_pred(pred):
        return true_fn(*ops) if pred else false_fn(*ops)

    def mk(fn):
        def body(_):
            out = fn(*ops)     # ops closed over: tracers ride the closure
            return jax.tree.map(
                _unwrap, out, is_leaf=lambda x: isinstance(x, Tensor))
        return body

    pv = jnp.asarray(_unwrap(pred)).astype(bool).reshape(())
    out = jax.lax.cond(pv, mk(true_fn), mk(false_fn), ())
    return jax.tree.map(
        lambda a: Tensor(a, stop_gradient=False)
        if isinstance(a, (jax.Array, jax.core.Tracer)) else a, out)


def convert_while(cond_fn, body_fn, init):
    """`while` rewrite target: evaluate the predicate once on the initial
    carry — python bool keeps a python loop; Tensor lowers to
    lax.while_loop with the assigned-locals tuple as carry."""
    first = cond_fn(*init)
    if not _is_tensor_pred(first):
        vals = tuple(init)
        ok = first
        while ok:
            vals = body_fn(*vals)
            ok = cond_fn(*vals)
            if _is_tensor_pred(ok):
                raise Dy2StaticTransformError(
                    "while predicate changed from python bool to Tensor "
                    "mid-loop; make it a Tensor from the start or use "
                    "paddle_tpu.jit.while_loop")
        return vals

    template = tuple(init)

    def c(carry):
        return jnp.asarray(
            _unwrap(cond_fn(*_wrap_like(carry, template)))
        ).astype(bool).reshape(())

    def b(carry):
        out = body_fn(*_wrap_like(carry, template))
        return tuple(jax.tree.map(
            _unwrap, tuple(out),
            is_leaf=lambda x: isinstance(x, Tensor)))

    init_arr = tuple(jax.tree.map(
        _unwrap, template, is_leaf=lambda x: isinstance(x, Tensor)))
    final = jax.lax.while_loop(c, b, init_arr)
    return tuple(_wrap_like(final, template))


# ---------------------------------------------------------------------------
# the AST transformer
# ---------------------------------------------------------------------------

class _AssignedNames(ast.NodeVisitor):
    """Names bound by a statement list (assign/augassign/for/with/etc.),
    not descending into nested function/class definitions."""

    def __init__(self):
        self.names: set[str] = set()

    def visit_FunctionDef(self, node):   # don't descend
        self.names.add(node.name)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_ClassDef(self, node):
        self.names.add(node.name)

    def visit_Name(self, node):
        if isinstance(node.ctx, (ast.Store, ast.Del)):
            self.names.add(node.id)

    def visit_arg(self, node):
        self.names.add(node.arg)


def _assigned(stmts):
    v = _AssignedNames()
    for s in stmts:
        v.visit(s)
    return v.names


def _has_return(stmts):
    for s in stmts:
        for n in ast.walk(s):
            if isinstance(n, ast.Return):
                return True
    return False


def _read_first(stmts):
    """Names whose FIRST use in this statement list is a Load —
    sequential approximation (nested branches merged, load wins).
    These must be fed into the extracted branch function as parameters,
    else python scoping turns `y = y * 2` into UnboundLocalError."""
    first: dict[str, str] = {}

    def note(name, kind):
        first.setdefault(name, kind)

    def walk_expr(node):
        for n in ast.walk(node):
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load):
                note(n.id, "load")

    def walk_stmt(s):
        if isinstance(s, (ast.Assign, ast.AnnAssign)):
            if s.value is not None:
                walk_expr(s.value)
            targets = s.targets if isinstance(s, ast.Assign) else [s.target]
            for t in targets:
                for n in ast.walk(t):
                    if isinstance(n, ast.Name) and isinstance(
                            n.ctx, ast.Store):
                        note(n.id, "store")
                    elif isinstance(n, ast.Name):
                        note(n.id, "load")   # x[i] = ... reads x
        elif isinstance(s, ast.AugAssign):
            walk_expr(s.value)
            for n in ast.walk(s.target):
                if isinstance(n, ast.Name):
                    note(n.id, "load")       # x += 1 reads x first
        elif isinstance(s, (ast.If, ast.While)):
            walk_expr(s.test)
            for b in (s.body, s.orelse):
                for st in b:
                    walk_stmt(st)
        elif isinstance(s, ast.For):
            walk_expr(s.iter)
            for n in ast.walk(s.target):
                if isinstance(n, ast.Name):
                    note(n.id, "store")
            for st in list(s.body) + list(s.orelse):
                walk_stmt(st)
        elif isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.ClassDef)):
            # a nested def BINDS its name (the transformer's own
            # _pt_true_N/_pt_false_N helpers land here). Decorators,
            # default values and class bodies evaluate AT the def
            # statement; the function body's free-variable reads are
            # deferred to call time but must still be bound in the
            # extracted scope — count both, minus names the inner
            # function binds itself.
            for dec in s.decorator_list:
                walk_expr(dec)
            if isinstance(s, ast.ClassDef):
                for base in list(s.bases) + [kw.value for kw in
                                             s.keywords]:
                    walk_expr(base)
                note(s.name, "store")
                for st in s.body:        # class bodies run immediately
                    walk_stmt(st)
            else:
                for d in (list(s.args.defaults)
                          + [d for d in s.args.kw_defaults
                             if d is not None]):
                    walk_expr(d)         # defaults run at def time
                note(s.name, "store")
                inner = ({a.arg for a in s.args.args}
                         | {a.arg for a in s.args.kwonlyargs}
                         | _assigned(s.body) | {s.name})
                if s.args.vararg:
                    inner.add(s.args.vararg.arg)
                if s.args.kwarg:
                    inner.add(s.args.kwarg.arg)
                for st in s.body:
                    for n in ast.walk(st):
                        if isinstance(n, ast.Name) \
                                and isinstance(n.ctx, ast.Load) \
                                and n.id not in inner:
                            note(n.id, "load")
        else:
            for n in ast.walk(s):
                if isinstance(n, ast.Name):
                    note(n.id, "load" if isinstance(n.ctx, ast.Load)
                         else "store")

    for s in stmts:
        walk_stmt(s)
    return {k for k, v in first.items() if v == "load"}


class _BreakFinder(ast.NodeVisitor):
    def __init__(self):
        self.found = False

    def visit_Break(self, node):
        self.found = True

    visit_Continue = visit_Break

    def visit_For(self, node):        # inner loops own their breaks
        pass

    visit_While = visit_For
    visit_FunctionDef = visit_For
    visit_AsyncFunctionDef = visit_For


def _has_break(stmts):
    f = _BreakFinder()
    for s in stmts:
        f.visit(s)
    return f.found


class _TailReturnNormalizer(ast.NodeTransformer):
    """`if p: ... return X` followed by more statements becomes
    `if p: ... return X else: <rest>` — semantically identical (the body
    path never falls through) and it turns the ubiquitous early-return
    pattern into the both-branches-return form the If rewrite accepts."""

    def _fix_body(self, stmts):
        out = []
        i = 0
        while i < len(stmts):
            s = stmts[i]
            rest = stmts[i + 1:]
            if (isinstance(s, ast.If) and s.body
                    and isinstance(s.body[-1], ast.Return)
                    and rest
                    and not (s.orelse
                             and isinstance(s.orelse[-1], ast.Return))):
                s.orelse = self._fix_body(list(s.orelse) + list(rest))
                out.append(self.visit(s))
                return out
            out.append(self.visit(s))
            i += 1
        return out

    def visit_FunctionDef(self, node):
        node.body = self._fix_body(node.body)
        return node

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_If(self, node):
        node.body = self._fix_body(node.body)
        node.orelse = self._fix_body(node.orelse)
        return node

    def visit_While(self, node):
        node.body = self._fix_body(node.body)
        return node

    visit_For = visit_While


class _CtrlFlowTransformer(ast.NodeTransformer):
    """Rewrite If/While into convert_ifelse/convert_while dispatch."""

    def __init__(self):
        self.counter = 0

    # -- if ---------------------------------------------------------------
    def visit_If(self, node):
        self.generic_visit(node)
        self.counter += 1
        n = self.counter
        body_ret = _has_return(node.body)
        else_ret = _has_return(node.orelse)

        if body_ret or else_ret:
            # only the tail form `if p: return X else: return Y` (possibly
            # with leading statements) maps onto cond cleanly
            if not (node.body and isinstance(node.body[-1], ast.Return)
                    and node.orelse
                    and isinstance(node.orelse[-1], ast.Return)):
                raise Dy2StaticTransformError(
                    f"line {node.lineno}: `return` inside a branch is "
                    "only supported when BOTH branches end in `return`; "
                    "restructure or use paddle_tpu.jit.cond")
            params = sorted(_read_first(node.body)
                            | _read_first(node.orelse))
            args = _params(params)
            tfn = _fdef(f"_pt_true_{n}", args, list(node.body))
            ffn = _fdef(f"_pt_false_{n}", args, list(node.orelse))
            ret = ast.Return(value=_call(
                "_pt_convert_ifelse",
                [node.test, ast.Name(f"_pt_true_{n}", ast.Load()),
                 ast.Name(f"_pt_false_{n}", ast.Load()),
                 _name_tuple(params)]))
            return [tfn, ffn, ret]

        stores_t = _assigned(node.body)
        stores_f = _assigned(node.orelse)
        bound_before = getattr(node, "_pt_bound_before", None)
        if bound_before is None:        # un-annotated (nested def): old rule
            names = sorted(stores_t | stores_f)
        else:
            # branch-local temps (assigned in ONE branch, no prior
            # binding) stay inside the extracted branch function — they
            # are not cond outputs and never read at the call site
            names = sorted(_if_outs(node, bound_before))
        # parameters: names the branches read before writing, plus out
        # names one branch passes through unchanged (it reads them for
        # the return tuple) — evaluated at the CALL SITE so python
        # scoping can't turn `y = y * 2` into UnboundLocalError
        params = sorted(
            _read_first(node.body) | _read_first(node.orelse)
            | {x for x in names if x not in stores_t or x not in stores_f})
        args = _params(params)
        out_tuple = ast.Tuple(
            elts=[ast.Name(x, ast.Load()) for x in names], ctx=ast.Load())
        tfn = _fdef(f"_pt_true_{n}", args,
                    list(node.body) + [ast.Return(out_tuple)])
        ffn = _fdef(f"_pt_false_{n}",
                    args, (list(node.orelse) or [ast.Pass()])
                    + [ast.Return(out_tuple)])
        assign = ast.Assign(
            targets=[ast.Tuple(
                elts=[ast.Name(x, ast.Store()) for x in names],
                ctx=ast.Store())],
            value=_call(
                "_pt_convert_ifelse",
                [node.test, ast.Name(f"_pt_true_{n}", ast.Load()),
                 ast.Name(f"_pt_false_{n}", ast.Load()),
                 _name_tuple(params)]))
        if not names:
            assign = ast.Expr(value=assign.value)
        return [tfn, ffn, assign]

    # -- while ------------------------------------------------------------
    def visit_While(self, node):
        self.generic_visit(node)
        if node.orelse:
            raise Dy2StaticTransformError(
                f"line {node.lineno}: while/else is not supported under "
                "to_static")
        if _has_return(node.body) or _has_break(node.body):
            raise Dy2StaticTransformError(
                f"line {node.lineno}: return/break/continue inside a "
                "`while` on a Tensor predicate cannot lower to "
                "lax.while_loop; restructure or use "
                "paddle_tpu.jit.while_loop")
        self.counter += 1
        n = self.counter
        # carry = names the body rebinds AND that live across iterations
        # (bound before / read-first / test-read); write-first temps stay
        # body-local. Everything else the test/body reads stays a
        # closure read (globals, helper fns, constants)
        bound_before = getattr(node, "_pt_bound_before", None)
        if bound_before is None:
            names = sorted(_assigned(node.body))
        else:
            names = sorted(_while_carries(node, bound_before))
        if not names:
            raise Dy2StaticTransformError(
                f"line {node.lineno}: `while` body assigns no locals — "
                "nothing to carry through lax.while_loop")
        args = ast.arguments(
            posonlyargs=[], args=[ast.arg(arg=x) for x in names],
            vararg=None, kwonlyargs=[], kw_defaults=[], kwarg=None,
            defaults=[])
        out_tuple = ast.Tuple(
            elts=[ast.Name(x, ast.Load()) for x in names], ctx=ast.Load())
        cfn = _fdef(f"_pt_wcond_{n}", args, [ast.Return(node.test)])
        bfn = _fdef(f"_pt_wbody_{n}", args,
                    list(node.body) + [ast.Return(out_tuple)])
        assign = ast.Assign(
            targets=[ast.Tuple(
                elts=[ast.Name(x, ast.Store()) for x in names],
                ctx=ast.Store())],
            value=_call(
                "_pt_convert_while",
                [ast.Name(f"_pt_wcond_{n}", ast.Load()),
                 ast.Name(f"_pt_wbody_{n}", ast.Load()),
                 ast.Tuple(elts=[ast.Name(x, ast.Load()) for x in names],
                           ctx=ast.Load())]))
        return [cfn, bfn, assign]


def _fdef(name, args, body):
    kw = {}
    import sys
    if sys.version_info >= (3, 12):
        kw["type_params"] = []
    return ast.FunctionDef(name=name, args=args, body=body,
                           decorator_list=[], returns=None,
                           type_comment=None, **kw)


def _noargs():
    return ast.arguments(posonlyargs=[], args=[], vararg=None,
                         kwonlyargs=[], kw_defaults=[], kwarg=None,
                         defaults=[])


def _params(names):
    return ast.arguments(
        posonlyargs=[], args=[ast.arg(arg=x) for x in names],
        vararg=None, kwonlyargs=[], kw_defaults=[], kwarg=None,
        defaults=[])


def _name_tuple(names):
    return ast.Tuple(elts=[ast.Name(x, ast.Load()) for x in names],
                     ctx=ast.Load())


def _call(name, args):
    return ast.Call(func=ast.Name(name, ast.Load()), args=args,
                    keywords=[])


def _uses_ctrl_flow(tree):
    for n in ast.walk(tree):
        if isinstance(n, (ast.If, ast.While)):
            return True
    return False


def _check_while_carries(fdef):
    """Reject (at transform time) any `while` whose body assigns a name
    that is not provably bound before the loop: visit_While makes every
    body-assigned local a lax.while_loop carry and reads it in the
    call-site init tuple, so an unbound carry is an UnboundLocalError at
    runtime with no eager fallback. Raising here instead routes the
    function through the existing Dy2StaticTransformError fallback
    (trace the original body)."""
    a = fdef.args
    bound = {x.arg for x in a.posonlyargs + a.args + a.kwonlyargs}
    if a.vararg:
        bound.add(a.vararg.arg)
    if a.kwarg:
        bound.add(a.kwarg.arg)
    _annotate_outside_loads(fdef)
    _check_block(fdef.body, bound)


def _test_reads(test):
    return {n.id for n in ast.walk(test)
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)}


def _annotate_outside_loads(fdef):
    """For each If/While in fdef, record the names LOADED anywhere in
    the function OUTSIDE that statement's own subtree — the liveness
    signal that distinguishes a private temp from a value the rest of
    the function consumes."""
    all_loads = [n for n in ast.walk(fdef)
                 if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)]
    for s in ast.walk(fdef):
        if isinstance(s, (ast.If, ast.While)):
            inside = set(map(id, ast.walk(s)))
            s._pt_outside_loads = frozenset(
                n.id for n in all_loads if id(n) not in inside)


def _while_carries(node, bound_before):
    """lax.while_loop carry = body-assigned names that are live OUTSIDE
    one iteration: bound before the loop, read-before-written in the
    body, read by the test, or read anywhere after/outside the loop.
    Pure write-first temps (incl. `_` unpacking slots) stay body-local —
    they caused spurious unbound-carry rejections (NOTES_r4
    'environment facts', now deleted)."""
    assigned = _assigned(node.body)
    outside = getattr(node, "_pt_outside_loads", frozenset())
    return assigned & (set(bound_before) | _read_first(node.body)
                       | _test_reads(node.test) | set(outside))


def _if_outs(node, bound_before):
    """Names the if-transform's call-site assign binds: assigned in BOTH
    branches (cond can produce them whichever side runs), or assigned in
    one branch with a pre-existing binding to pass through. One-branch
    temps with no prior binding are private to the branch body —
    _check_block rejects them at transform time (-> eager fallback) if
    the rest of the function reads them, since lax.cond cannot produce
    a value with no else-side initial."""
    st, sf = _assigned(node.body), _assigned(node.orelse)
    return {x for x in st | sf
            if x in bound_before or (x in st and x in sf)}


def _check_block(stmts, bound):
    for s in stmts:
        if isinstance(s, ast.While):
            s._pt_bound_before = frozenset(bound)
            carries = _while_carries(s, bound)
            missing = sorted(carries - bound)
            if missing:
                raise Dy2StaticTransformError(
                    f"line {s.lineno}: `while` carries "
                    f"{', '.join(missing)} read before any binding; "
                    "lax.while_loop carries need an initial value — "
                    "initialize it before the loop")
            _check_block(s.body, set(bound) | _assigned(s.body))
            bound |= carries          # call-site assign rebinds carries
        elif isinstance(s, ast.If):
            s._pt_bound_before = frozenset(bound)
            st_a, sf_a = _assigned(s.body), _assigned(s.orelse)
            dropped = {x for x in (st_a ^ sf_a) if x not in bound}
            leaked = sorted(dropped
                            & getattr(s, "_pt_outside_loads", frozenset()))
            if leaked:
                raise Dy2StaticTransformError(
                    f"line {s.lineno}: {', '.join(leaked)} is assigned in "
                    "only one `if` branch but read after it; lax.cond "
                    "needs a value from both sides — bind it before the "
                    "`if` or in both branches")
            bt, bf = set(bound), set(bound)
            _check_block(s.body, bt)
            _check_block(s.orelse, bf)
            # the if-transform's call-site assign binds visit_If `names`
            bound |= _if_outs(s, bound)
        elif isinstance(s, ast.For):
            for n in ast.walk(s.target):
                if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store):
                    bound.add(n.id)
            # lenient: python `for` bodies usually run ≥1 time in traced
            # code; treat their assignments as binding
            _check_block(s.body, bound)
            bound |= _assigned(s.body)
        elif isinstance(s, ast.With):
            for item in s.items:
                if item.optional_vars is not None:
                    for n in ast.walk(item.optional_vars):
                        if isinstance(n, ast.Name) and isinstance(
                                n.ctx, ast.Store):
                            bound.add(n.id)
            _check_block(s.body, bound)
        elif isinstance(s, ast.Try):
            _check_block(s.body, bound)
            for h in s.handlers:
                _check_block(h.body, set(bound))
            _check_block(s.finalbody, bound)
        else:
            # assign/augassign/annassign/import/def/walrus-in-expr — the
            # same binder the while-transform uses to compute carries
            bound |= _assigned([s])


# fn.__code__ -> None (nothing to transform) | (compiled module, fdef name).
# Only the SOURCE transform is memoized by code object — closure values are
# bound per function instance below, so two closures created from the same
# factory do not share captured values.
_transform_memo: dict = {}
_instance_memo: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def ast_transform(fn):
    """Source-to-source rewrite of `fn` routing if/while through the
    convert_* dispatchers. Returns the transformed function, or None if
    `fn` has no if/while (nothing to do). Raises
    Dy2StaticTransformError for unsupported shapes."""
    try:
        cached = _instance_memo.get(fn)
    except TypeError:
        cached = None
    if cached is not None:
        return cached
    key = getattr(fn, "__code__", None)
    if key not in _transform_memo:
        _transform_memo[key] = _compile_transform(fn, key)
    entry = _transform_memo[key]
    if entry is None:
        return None
    code, fname = entry

    glb = dict(fn.__globals__)
    glb["_pt_convert_ifelse"] = convert_ifelse
    glb["_pt_convert_while"] = convert_while
    # closures: snapshot THIS instance's freevars (cells are read-only
    # here); never shared across instances of the same code object
    if fn.__closure__:
        for name, cell in zip(fn.__code__.co_freevars, fn.__closure__):
            try:
                glb[name] = cell.cell_contents
            except ValueError:
                pass
    loc: dict = {}
    exec(code, glb, loc)
    new_fn = functools.wraps(fn)(loc[fname])
    # wraps() sets new_fn.__wrapped__ = fn: a strong value→key reference
    # would make every WeakKeyDictionary entry immortal (and pin the
    # globals snapshot). Drop it so instances are evicted with their fn.
    del new_fn.__wrapped__
    try:
        _instance_memo[fn] = new_fn
    except TypeError:
        pass
    return new_fn


def _compile_transform(fn, key):
    try:
        src = textwrap.dedent(inspect.getsource(fn))
    except (OSError, TypeError):
        return None
    tree = ast.parse(src)
    fdef = tree.body[0]
    if not isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return None
    if not _uses_ctrl_flow(fdef):
        return None
    _check_while_carries(fdef)
    fdef.decorator_list = []          # drop @to_static etc.
    tree = _TailReturnNormalizer().visit(tree)
    new_tree = _CtrlFlowTransformer().visit(tree)
    ast.fix_missing_locations(new_tree)
    return (compile(new_tree, f"<dy2static:{fn.__qualname__}>", "exec"),
            fdef.name)
