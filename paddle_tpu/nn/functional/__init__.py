"""paddle_tpu.nn.functional (reference: python/paddle/nn/functional/__init__.py)."""
from paddle_tpu.nn.functional.activation import *  # noqa: F401,F403
from paddle_tpu.nn.functional.common import *  # noqa: F401,F403
from paddle_tpu.nn.functional.conv import *  # noqa: F401,F403
from paddle_tpu.nn.functional.pooling import *  # noqa: F401,F403
from paddle_tpu.nn.functional.norm import *  # noqa: F401,F403
from paddle_tpu.nn.functional.loss import *  # noqa: F401,F403
from paddle_tpu.nn.functional.attention import (  # noqa: F401
    scaled_dot_product_attention, flash_attention)
from paddle_tpu.tensor.manipulation import pad  # noqa: F401


from paddle_tpu.nn.functional.extras import *  # noqa: F401,F403,E402
from paddle_tpu.nn.functional.extras import (  # noqa: F401,E402
    hardtanh_, leaky_relu_, tanh_, thresholded_relu_)
