"""Loss functions (reference: python/paddle/nn/functional/loss.py)."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.core.dispatch import defop
from paddle_tpu.core.tensor import Tensor


def _reduce(val, reduction):
    if reduction == "mean":
        return jnp.mean(val)
    if reduction == "sum":
        return jnp.sum(val)
    return val


@jax.custom_vjp
def _ce_mean_fused(logits, labels, ignore_index):
    """Mean softmax-CE over int labels WITHOUT materializing the f32
    log-softmax. The generic path keeps a (N, V) f32 log_softmax as the
    AD residual — ~1 GB at LLM shapes (N=8k, V=32k) written fwd and
    re-read bwd. Here the fwd keeps only lse (N,) f32 and the bwd
    recomputes softmax from the bf16 logits in one fused pass:
    dlogits = (softmax - onehot) * g * valid / count."""
    loss, _ = _ce_mean_fused_fwd(logits, labels, ignore_index)
    return loss


def _ce_mean_fused_fwd(logits, labels, ignore_index):
    # keep the max pass and the label gather in the logits dtype (both
    # exact for bf16) so the f32 convert has exactly ONE consumer (the
    # exp pass) and fuses — a shared `logits.astype(f32)` made XLA
    # materialize the full (N, V) f32 logits (~1.5 GB at bench shapes)
    # as an extra output of the lm_head matmul
    m = jnp.max(logits, axis=-1).astype(jnp.float32)
    sumexp = jnp.sum(
        jnp.exp(logits.astype(jnp.float32) - m[..., None]), axis=-1)
    lse = m + jnp.log(sumexp)
    picked = jnp.take_along_axis(
        logits, labels[..., None], axis=-1)[..., 0].astype(jnp.float32)
    valid = labels != ignore_index
    count = jnp.maximum(jnp.sum(valid.astype(jnp.float32)), 1.0)
    loss = jnp.sum(jnp.where(valid, lse - picked, 0.0)) / count
    return loss, (logits, labels, lse, valid, count)


def _ce_mean_fused_bwd(res, g):
    logits, labels, lse, valid, count = res
    scale = (g / count) * valid.astype(jnp.float32)          # (N,)
    # softmax in the logits dtype: one read of logits, one write of
    # dlogits, no f32 intermediate
    p = jnp.exp(logits.astype(jnp.float32) - lse[..., None])
    onehot = (jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
              == labels[..., None])
    d = (p - onehot.astype(jnp.float32)) * scale[..., None]
    # NOTE: XLA recomputes this exp pass inside both lm_head backward
    # matmuls (dx and dW); materializing dlogits once behind an
    # optimization_barrier measured SLOWER (45.9k vs 46.6k tok/s)
    return d.astype(logits.dtype), None, None


_ce_mean_fused.defvjp(_ce_mean_fused_fwd, _ce_mean_fused_bwd)


@defop("blockwise_ce", amp_policy="white",
       spmd_note="row (batch*seq) axis freely shardable; the vocab "
                 "axis streams in chunks, so vocab sharding composes "
                 "with GSPMD like the dense matmul it replaces")
def _blockwise_ce(hidden, weight, label, chunk, vocab_block=0,
                  ignore_index=-100, transpose_w=False, kernel=None):
    """Hidden->vocab projection fused with softmax-CE, streamed so the
    [N, V] logits never materialize in forward OR backward
    (kernels/blockwise_ce.py; the train-path memory cap ISSUE 14
    removes). `transpose_w` takes the tied-embedding (V, D) layout —
    the transpose happens inside the op, so jax AD routes dW back in
    the embedding's own layout."""
    from paddle_tpu.kernels.blockwise_ce import blockwise_ce_loss
    w = weight.T if transpose_w else weight
    return blockwise_ce_loss(hidden, w, label, chunk=chunk,
                             vocab_block=vocab_block,
                             ignore_index=ignore_index, kernel=kernel)


def blockwise_cross_entropy(hidden, weight, label, chunk, vocab_block=0,
                            ignore_index=-100, transpose_w=False,
                            kernel=None, name=None):
    """Mean CE of `hidden @ weight` vs int `label` without the [N, V]
    logits tensor (the blockwise train loss; see
    kernels/blockwise_ce.py for the streaming/vjp design). hidden
    (N, D), weight (D, V) — or (V, D) with transpose_w=True — label
    (N,). `chunk` rows stream per block; peak logits-shaped
    intermediate is (chunk, vocab_block or V)."""
    return _blockwise_ce(hidden, weight, label, chunk=chunk,
                         vocab_block=vocab_block,
                         ignore_index=ignore_index,
                         transpose_w=transpose_w, kernel=kernel)


@defop("cross_entropy", amp_policy="black",
       spmd_note="vocab-sharded logits -> ParallelCrossEntropy "
                 "(reference: mp_layers.py:743); here sharded softmax is "
                 "GSPMD-automatic")
def _cross_entropy(input, label, weight=None, ignore_index=-100,
                   reduction="mean", soft_label=False, axis=-1,
                   use_softmax=True, label_smoothing=0.0):
    # fast path for the LLM pretrain shape: 2D logits, int labels, mean
    # reduction, no weights/smoothing — avoids the (N, V) f32 residual
    if (not soft_label and use_softmax and weight is None
            and label_smoothing == 0.0 and reduction == "mean"
            and axis in (-1, input.ndim - 1) and input.ndim == 2
            and label.ndim == 1
            and not jnp.issubdtype(label.dtype, jnp.floating)):
        return _ce_mean_fused(input, label.astype(jnp.int32),
                              ignore_index)
    logits = input.astype(jnp.float32)
    if soft_label:
        lab = label.astype(jnp.float32)
        if label_smoothing > 0.0:
            k = logits.shape[axis]
            lab = (1 - label_smoothing) * lab + label_smoothing / k
        logp = jax.nn.log_softmax(logits, axis=axis) if use_softmax \
            else jnp.log(jnp.clip(logits, 1e-15))
        loss = -jnp.sum(lab * logp, axis=axis)
        return _reduce(loss, reduction)
    lab = label
    if lab.ndim == logits.ndim and lab.shape[axis] == 1:
        lab = jnp.squeeze(lab, axis)
    lab = lab.astype(jnp.int32)
    if use_softmax:
        logp = jax.nn.log_softmax(logits, axis=axis)
    else:
        logp = jnp.log(jnp.clip(logits, 1e-15))
    if label_smoothing > 0.0:
        k = logits.shape[axis]
        nll = -jnp.take_along_axis(
            logp, lab[..., None] if axis in (-1, logits.ndim - 1)
            else jnp.expand_dims(lab, axis), axis=axis).squeeze(axis)
        smooth = -jnp.mean(logp, axis=axis)
        loss = (1 - label_smoothing) * nll + label_smoothing * smooth
    else:
        loss = -jnp.take_along_axis(
            logp, jnp.expand_dims(lab, axis), axis=axis).squeeze(axis)
    valid = (lab != ignore_index)
    loss = jnp.where(valid, loss, 0.0)
    if weight is not None:
        w = jnp.take(weight, jnp.clip(lab, 0), axis=0)
        loss = loss * w
        if reduction == "mean":
            return jnp.sum(loss) / jnp.maximum(
                jnp.sum(jnp.where(valid, w, 0.0)), 1e-12)
    if reduction == "mean":
        return jnp.sum(loss) / jnp.maximum(
            jnp.sum(valid.astype(jnp.float32)), 1.0)
    return _reduce(loss, reduction)


def cross_entropy(input, label, weight=None, ignore_index=-100,
                  reduction="mean", soft_label=False, axis=-1,
                  use_softmax=True, label_smoothing=0.0, name=None):
    return _cross_entropy(input, label, weight=weight,
                          ignore_index=ignore_index, reduction=reduction,
                          soft_label=soft_label, axis=axis,
                          use_softmax=use_softmax,
                          label_smoothing=label_smoothing)


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=True,
                               return_softmax=False, axis=-1):
    loss = _cross_entropy(logits, label, reduction="none",
                          soft_label=soft_label, ignore_index=ignore_index,
                          axis=axis)
    from paddle_tpu.nn.functional.activation import softmax as _softmax
    from paddle_tpu.tensor.manipulation import unsqueeze
    loss = unsqueeze(loss, axis)
    if return_softmax:
        return loss, _softmax(logits, axis=axis)
    return loss


@defop("mse_loss")
def _mse_loss(input, label, reduction="mean"):
    return _reduce(jnp.square(input - label), reduction)


def mse_loss(input, label, reduction="mean", name=None):
    return _mse_loss(input, label, reduction=reduction)


@defop("l1_loss")
def _l1_loss(input, label, reduction="mean"):
    return _reduce(jnp.abs(input - label), reduction)


def l1_loss(input, label, reduction="mean", name=None):
    return _l1_loss(input, label, reduction=reduction)


@defop("smooth_l1_loss")
def _smooth_l1(input, label, reduction="mean", delta=1.0):
    d = input - label
    ad = jnp.abs(d)
    loss = jnp.where(ad < delta, 0.5 * d * d / delta, ad - 0.5 * delta)
    return _reduce(loss, reduction)


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    return _smooth_l1(input, label, reduction=reduction, delta=delta)


@defop("nll_loss_op", amp_policy="black")
def _nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean"):
    lab = label.astype(jnp.int32)
    loss = -jnp.take_along_axis(input, lab[:, None] if input.ndim == 2
                                else jnp.expand_dims(lab, 1), axis=1)
    loss = loss.squeeze(1)
    valid = lab != ignore_index
    loss = jnp.where(valid, loss, 0.0)
    if weight is not None:
        w = jnp.take(weight, jnp.clip(lab, 0), axis=0)
        loss = loss * w
        if reduction == "mean":
            return jnp.sum(loss) / jnp.sum(jnp.where(valid, w, 0.0))
    if reduction == "mean":
        return jnp.sum(loss) / jnp.maximum(jnp.sum(valid), 1)
    return _reduce(loss, reduction)


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean",
             name=None):
    return _nll_loss(input, label, weight=weight, ignore_index=ignore_index,
                     reduction=reduction)


@defop("binary_cross_entropy", amp_policy="black")
def _bce(input, label, weight=None, reduction="mean"):
    x = jnp.clip(input.astype(jnp.float32), 1e-12, 1.0 - 1e-12)
    loss = -(label * jnp.log(x) + (1 - label) * jnp.log(1 - x))
    if weight is not None:
        loss = loss * weight
    return _reduce(loss, reduction)


def binary_cross_entropy(input, label, weight=None, reduction="mean",
                         name=None):
    return _bce(input, label, weight=weight, reduction=reduction)


@defop("bce_with_logits", amp_policy="black")
def _bce_logits(logit, label, weight=None, pos_weight=None, reduction="mean"):
    # PROMOTE to at least f32 (bf16/f16 upcast for stability) without
    # downcasting f64 — forcing f32 made the x64 numeric-grad check
    # noise-limited (the analytic grad was always exact)
    acc = jnp.promote_types(logit.dtype, jnp.float32)
    x = logit.astype(acc)
    lab = label.astype(acc)
    max_val = jnp.clip(-x, 0, None)
    if pos_weight is not None:
        log_w = (pos_weight - 1) * lab + 1
        loss = (1 - lab) * x + log_w * (
            jnp.log1p(jnp.exp(-jnp.abs(x))) + max_val)
    else:
        loss = (1 - lab) * x + jnp.log1p(jnp.exp(-jnp.abs(x))) + max_val
    if weight is not None:
        loss = loss * weight
    return _reduce(loss, reduction)


def binary_cross_entropy_with_logits(logit, label, weight=None,
                                     reduction="mean", pos_weight=None,
                                     name=None):
    return _bce_logits(logit, label, weight=weight, pos_weight=pos_weight,
                       reduction=reduction)


@defop("kl_div_op", amp_policy="black")
def _kl_div(input, label, reduction="mean", log_target=False):
    if log_target:
        loss = jnp.exp(label) * (label - input)
    else:
        loss = label * (jnp.log(jnp.clip(label, 1e-12)) - input)
    if reduction == "batchmean":
        return jnp.sum(loss) / input.shape[0]
    return _reduce(loss, reduction)


def kl_div(input, label, reduction="mean", log_target=False, name=None):
    return _kl_div(input, label, reduction=reduction, log_target=log_target)


@defop("margin_ranking")
def _margin_ranking(input, other, label, margin=0.0, reduction="mean"):
    loss = jnp.clip(-label * (input - other) + margin, 0, None)
    return _reduce(loss, reduction)


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean",
                        name=None):
    return _margin_ranking(input, other, label, margin=margin,
                           reduction=reduction)


@defop("hinge_embedding")
def _hinge_embedding(input, label, margin=1.0, reduction="mean"):
    loss = jnp.where(label == 1.0, input,
                     jnp.clip(margin - input, 0, None))
    return _reduce(loss, reduction)


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean",
                         name=None):
    return _hinge_embedding(input, label, margin=margin, reduction=reduction)


@defop("cosine_embedding")
def _cosine_embedding(input1, input2, label, margin=0.0, reduction="mean"):
    cos = jnp.sum(input1 * input2, -1) / jnp.maximum(
        jnp.linalg.norm(input1, axis=-1) * jnp.linalg.norm(input2, axis=-1),
        1e-12)
    loss = jnp.where(label == 1, 1 - cos, jnp.clip(cos - margin, 0, None))
    return _reduce(loss, reduction)


def cosine_embedding_loss(input1, input2, label, margin=0,
                          reduction="mean", name=None):
    return _cosine_embedding(input1, input2, label, margin=margin,
                             reduction=reduction)


@defop("triplet_margin")
def _triplet_margin(input, positive, negative, margin=1.0, p=2.0,
                    epsilon=1e-6, swap=False, reduction="mean"):
    def dist(a, b):
        return jnp.power(jnp.sum(jnp.power(jnp.abs(a - b) + epsilon, p), -1),
                         1.0 / p)
    dp = dist(input, positive)
    dn = dist(input, negative)
    if swap:
        dn = jnp.minimum(dn, dist(positive, negative))
    return _reduce(jnp.clip(dp - dn + margin, 0, None), reduction)


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2,
                        epsilon=1e-6, swap=False, reduction="mean", name=None):
    return _triplet_margin(input, positive, negative, margin=margin, p=p,
                           epsilon=epsilon, swap=swap, reduction=reduction)


@defop("log_loss_op", amp_policy="black")
def _log_loss(input, label, epsilon=1e-4):
    x = jnp.clip(input, epsilon, 1 - epsilon)
    return -label * jnp.log(x) - (1 - label) * jnp.log(1 - x)


def log_loss(input, label, epsilon=1e-4, name=None):
    return _log_loss(input, label, epsilon=epsilon)


def square_error_cost(input, label):
    from paddle_tpu.tensor import math as M
    return M.square(input - label)


@defop("sigmoid_focal_loss_op", amp_policy="black")
def _sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                        reduction="sum"):
    p = jax.nn.sigmoid(logit)
    ce = (1 - label) * logit + jnp.log1p(jnp.exp(-jnp.abs(logit))) + \
        jnp.clip(-logit, 0, None)
    p_t = p * label + (1 - p) * (1 - label)
    loss = ce * jnp.power(1 - p_t, gamma)
    if alpha >= 0:
        a_t = alpha * label + (1 - alpha) * (1 - label)
        loss = a_t * loss
    if normalizer is not None:
        loss = loss / normalizer
    return _reduce(loss, reduction)


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    return _sigmoid_focal_loss(logit, label, normalizer=normalizer,
                               alpha=alpha, gamma=gamma, reduction=reduction)


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    raise NotImplementedError(
        "ctc_loss pending: needs a lax.scan alpha-recursion implementation")
