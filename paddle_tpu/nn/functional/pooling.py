"""Pooling (reference: python/paddle/nn/functional/pooling.py).

reduce_window is XLA's native pooling primitive — direct MXU-adjacent VPU
work, no cuDNN descriptor plumbing needed.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.core.dispatch import defop


def _tuple(v, n):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in v)
    return (int(v),) * n


def _pool_pad(padding, n):
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, (list, tuple)):
        flat = list(padding)
        if len(flat) == n:
            return [(int(p), int(p)) for p in flat]
        if len(flat) == 2 * n:
            return [(int(flat[2 * i]), int(flat[2 * i + 1])) for i in range(n)]
    return [(int(padding), int(padding))] * n


def _reduce_window(x, init, op, window, strides, padding, n):
    dims = (1, 1) + window
    strd = (1, 1) + strides
    if isinstance(padding, str):
        pad = padding
    else:
        pad = [(0, 0), (0, 0)] + list(padding)
    return jax.lax.reduce_window(x, init, op, dims, strd, pad)


@defop("max_pool2d")
def _max_pool2d(x, kernel_size, stride, padding, ceil_mode=False):
    return _reduce_window(x, -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating)
                          else jnp.iinfo(x.dtype).min,
                          jax.lax.max, kernel_size, stride, padding, 2)


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    ks = _tuple(kernel_size, 2)
    st = _tuple(stride, 2) if stride is not None else ks
    out = _max_pool2d(x, kernel_size=ks, stride=st,
                      padding=_pool_pad(padding, 2), ceil_mode=ceil_mode)
    if return_mask:
        idx = _max_pool2d_indices(x, kernel_size=ks, stride=st,
                                  padding=_pool_pad(padding, 2))
        return out, idx
    return out


@defop("max_pool2d_indices", differentiable=False)
def _max_pool2d_indices(x, kernel_size, stride, padding):
    n, c, h, w = x.shape
    flat_idx = jnp.arange(h * w, dtype=jnp.float32).reshape(1, 1, h, w)
    flat_idx = jnp.broadcast_to(flat_idx, x.shape)
    # select index of max via reduce_window over (value, index) pairs
    def sel(a, b):
        av, ai = a
        bv, bi = b
        take_b = bv > av
        return jnp.where(take_b, bv, av), jnp.where(take_b, bi, ai)
    init = (-jnp.inf, jnp.float32(-1))
    vals, idxs = jax.lax.reduce_window(
        (x.astype(jnp.float32), flat_idx), init, sel,
        (1, 1) + kernel_size, (1, 1) + stride,
        [(0, 0), (0, 0)] + list(padding))
    return idxs.astype(jnp.int64)


@defop("avg_pool2d")
def _avg_pool2d(x, kernel_size, stride, padding, exclusive=True):
    summed = _reduce_window(x, 0.0, jax.lax.add, kernel_size, stride,
                            padding, 2)
    if exclusive and padding != "VALID" and any(
            p != (0, 0) for p in (padding if isinstance(padding, list) else [])):
        ones = jnp.ones_like(x)
        counts = _reduce_window(ones, 0.0, jax.lax.add, kernel_size, stride,
                                padding, 2)
        return summed / counts
    return summed / float(np.prod(kernel_size))


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW",
               name=None):
    ks = _tuple(kernel_size, 2)
    st = _tuple(stride, 2) if stride is not None else ks
    out = _avg_pool2d(x, kernel_size=ks, stride=st,
                      padding=_pool_pad(padding, 2), exclusive=exclusive)
    if divisor_override:
        out = out * (float(np.prod(ks)) / divisor_override)
    return out


@defop("max_pool1d")
def _max_pool1d(x, kernel_size, stride, padding):
    return _reduce_window(x, -jnp.inf, jax.lax.max, kernel_size, stride,
                          padding, 1)


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, name=None):
    ks = _tuple(kernel_size, 1)
    st = _tuple(stride, 1) if stride is not None else ks
    return _max_pool1d(x, kernel_size=ks, stride=st,
                       padding=_pool_pad(padding, 1))


@defop("avg_pool1d")
def _avg_pool1d(x, kernel_size, stride, padding, exclusive=True):
    s = _reduce_window(x, 0.0, jax.lax.add, kernel_size, stride, padding, 1)
    return s / float(np.prod(kernel_size))


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, name=None):
    ks = _tuple(kernel_size, 1)
    st = _tuple(stride, 1) if stride is not None else ks
    return _avg_pool1d(x, kernel_size=ks, stride=st,
                       padding=_pool_pad(padding, 1), exclusive=exclusive)


@defop("max_pool3d")
def _max_pool3d(x, kernel_size, stride, padding):
    return _reduce_window(x, -jnp.inf, jax.lax.max, kernel_size, stride,
                          padding, 3)


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    ks = _tuple(kernel_size, 3)
    st = _tuple(stride, 3) if stride is not None else ks
    return _max_pool3d(x, kernel_size=ks, stride=st,
                       padding=_pool_pad(padding, 3))


@defop("avg_pool3d")
def _avg_pool3d(x, kernel_size, stride, padding):
    s = _reduce_window(x, 0.0, jax.lax.add, kernel_size, stride, padding, 3)
    return s / float(np.prod(kernel_size))


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW",
               name=None):
    ks = _tuple(kernel_size, 3)
    st = _tuple(stride, 3) if stride is not None else ks
    return _avg_pool3d(x, kernel_size=ks, stride=st,
                       padding=_pool_pad(padding, 3))


# ---- adaptive pooling --------------------------------------------------
@defop("adaptive_avg_pool2d")
def _adaptive_avg_pool2d(x, output_size):
    n, c, h, w = x.shape
    oh, ow = output_size
    if h % oh == 0 and w % ow == 0:
        x4 = x.reshape(n, c, oh, h // oh, ow, w // ow)
        return x4.mean(axis=(3, 5))
    # general case: mean over variable windows
    out = jnp.zeros((n, c, oh, ow), x.dtype)
    hs = [(i * h) // oh for i in range(oh)] + [h]
    ws = [(j * w) // ow for j in range(ow)] + [w]
    rows = []
    for i in range(oh):
        cols = []
        for j in range(ow):
            cols.append(x[:, :, hs[i]:hs[i + 1], ws[j]:ws[j + 1]]
                        .mean(axis=(2, 3)))
        rows.append(jnp.stack(cols, axis=-1))
    return jnp.stack(rows, axis=-2)


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive_avg_pool2d(x, output_size=_tuple(output_size, 2))


@defop("adaptive_max_pool2d")
def _adaptive_max_pool2d(x, output_size):
    n, c, h, w = x.shape
    oh, ow = output_size
    if h % oh == 0 and w % ow == 0:
        x4 = x.reshape(n, c, oh, h // oh, ow, w // ow)
        return x4.max(axis=(3, 5))
    hs = [(i * h) // oh for i in range(oh)] + [h]
    ws = [(j * w) // ow for j in range(ow)] + [w]
    rows = []
    for i in range(oh):
        cols = []
        for j in range(ow):
            cols.append(x[:, :, hs[i]:hs[i + 1], ws[j]:ws[j + 1]]
                        .max(axis=(2, 3)))
        rows.append(jnp.stack(cols, axis=-1))
    return jnp.stack(rows, axis=-2)


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    return _adaptive_max_pool2d(x, output_size=_tuple(output_size, 2))


@defop("adaptive_avg_pool1d")
def _adaptive_avg_pool1d(x, output_size):
    n, c, l = x.shape
    o = output_size
    if l % o == 0:
        return x.reshape(n, c, o, l // o).mean(axis=3)
    bounds = [(i * l) // o for i in range(o)] + [l]
    return jnp.stack([x[:, :, bounds[i]:bounds[i + 1]].mean(axis=2)
                      for i in range(o)], axis=-1)


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive_avg_pool1d(x, output_size=int(output_size))


@defop("adaptive_max_pool1d")
def _adaptive_max_pool1d(x, output_size):
    n, c, l = x.shape
    o = output_size
    if l % o == 0:
        return x.reshape(n, c, o, l // o).max(axis=3)
    bounds = [(i * l) // o for i in range(o)] + [l]
    return jnp.stack([x[:, :, bounds[i]:bounds[i + 1]].max(axis=2)
                      for i in range(o)], axis=-1)


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    return _adaptive_max_pool1d(x, output_size=int(output_size))


@defop("adaptive_avg_pool3d")
def _adaptive_avg_pool3d(x, output_size):
    n, c, d, h, w = x.shape
    od, oh, ow = output_size
    if d % od == 0 and h % oh == 0 and w % ow == 0:
        x6 = x.reshape(n, c, od, d // od, oh, h // oh, ow, w // ow)
        return x6.mean(axis=(3, 5, 7))
    raise NotImplementedError("adaptive_avg_pool3d with non-divisible sizes")


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive_avg_pool3d(x, output_size=_tuple(output_size, 3))


def lp_pool2d(x, norm_type, kernel_size, stride=None, padding=0,
              ceil_mode=False, data_format="NCHW", name=None):
    from paddle_tpu.tensor import math as M
    p = float(norm_type)
    xp = M.pow(M.abs(x), p)
    pooled = avg_pool2d(xp, kernel_size, stride, padding,
                        ceil_mode=ceil_mode, exclusive=False)
    ks = _tuple(kernel_size, 2)
    return M.pow(pooled * float(np.prod(ks)), 1.0 / p)
