"""Long-tail nn.functional ops (reference: python/paddle/nn/functional/
{pooling,loss,vision,common}.py entries not in the core modules; native
kernels being replaced: warprnnt (rnnt_loss), grid_sampler CUDA kernel).
"""
from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.core.dispatch import defop
from paddle_tpu.core.tensor import Tensor

__all__ = [
    'adaptive_max_pool3d', 'fractional_max_pool2d', 'fractional_max_pool3d',
    'max_unpool1d', 'max_unpool2d', 'max_unpool3d', 'affine_grid',
    'grid_sample', 'class_center_sample', 'dice_loss', 'gaussian_nll_loss',
    'hsigmoid_loss', 'margin_cross_entropy', 'multi_label_soft_margin_loss',
    'multi_margin_loss', 'npair_loss', 'pairwise_distance',
    'poisson_nll_loss', 'rnnt_loss', 'soft_margin_loss', 'sparse_attention',
    'triplet_margin_with_distance_loss', 'zeropad2d', 'gather_tree',
]


def _arr(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


# -- pooling ---------------------------------------------------------------

@defop("adaptive_max_pool3d")
def _adaptive_max_pool3d(x, output_size):
    # x: (N, C, D, H, W); divisible dims take the reshape fast path like
    # the 2D implementation (pooling.py _adaptive_max_pool2d)
    n, c, d, h, w = x.shape
    od, oh, ow = output_size
    if d % od == 0 and h % oh == 0 and w % ow == 0:
        r = x.reshape(n, c, od, d // od, oh, h // oh, ow, w // ow)
        return jnp.max(r, axis=(3, 5, 7))

    def bounds(size, out):
        return [((i * size) // out,
                 max(((i + 1) * size + out - 1) // out,
                     (i * size) // out + 1)) for i in range(out)]
    db, hb, wb = bounds(d, od), bounds(h, oh), bounds(w, ow)
    planes = []
    for (d0, d1) in db:
        rows = []
        for (h0, h1) in hb:
            cells = [jnp.max(x[:, :, d0:d1, h0:h1, w0:w1], axis=(2, 3, 4))
                     for (w0, w1) in wb]
            rows.append(jnp.stack(cells, axis=-1))
        planes.append(jnp.stack(rows, axis=-2))
    return jnp.stack(planes, axis=-3)


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    if isinstance(output_size, int):
        output_size = (output_size,) * 3
    out = _adaptive_max_pool3d(x, tuple(output_size))
    if return_mask:
        raise NotImplementedError("return_mask unsupported on TPU path")
    return out


def _fractional_pool(x, output_size, kernel_size, random_u, ndim):
    spatial = x.shape[2:]
    outs = list(output_size)
    u = random_u if random_u is not None else 0.5
    # pseudo-random (deterministic given u) region boundaries, per the
    # fractional max-pooling paper's alpha-sequence construction
    idxs = []
    for s, o in zip(spatial, outs):
        alpha = s / o
        seq = [int(math.ceil(alpha * (i + u))) - int(math.ceil(alpha * u))
               for i in range(o + 1)]
        seq[-1] = s
        idxs.append(seq)
    return outs, idxs


@defop("fractional_max_pool2d")
def _fractional_max_pool2d(x, output_size, random_u):
    outs, (rows, cols) = _fractional_pool(x, output_size, None, random_u, 2)
    oh, ow = outs
    n, c = x.shape[:2]
    out = jnp.zeros((n, c, oh, ow), x.dtype)
    for i in range(oh):
        for j in range(ow):
            out = out.at[:, :, i, j].set(jnp.max(
                x[:, :, rows[i]:max(rows[i + 1], rows[i] + 1),
                  cols[j]:max(cols[j + 1], cols[j] + 1)], axis=(2, 3)))
    return out


def _sample_u(random_u):
    if random_u is not None:
        return float(random_u)
    from paddle_tpu.core.random import next_key
    return float(jax.random.uniform(next_key(), (), jnp.float32, 1e-3,
                                    1 - 1e-3))


def fractional_max_pool2d(x, output_size, kernel_size=None, random_u=None,
                          return_mask=False, name=None):
    if return_mask:
        raise NotImplementedError("return_mask unsupported on TPU path")
    if isinstance(output_size, int):
        output_size = (output_size,) * 2
    return _fractional_max_pool2d(x, tuple(output_size),
                                  _sample_u(random_u))


@defop("fractional_max_pool3d")
def _fractional_max_pool3d(x, output_size, random_u):
    outs, (ds, rows, cols) = _fractional_pool(x, output_size, None,
                                              random_u, 3)
    od, oh, ow = outs
    n, c = x.shape[:2]
    out = jnp.zeros((n, c, od, oh, ow), x.dtype)
    for z in range(od):
        for i in range(oh):
            for j in range(ow):
                out = out.at[:, :, z, i, j].set(jnp.max(
                    x[:, :, ds[z]:max(ds[z + 1], ds[z] + 1),
                      rows[i]:max(rows[i + 1], rows[i] + 1),
                      cols[j]:max(cols[j + 1], cols[j] + 1)],
                    axis=(2, 3, 4)))
    return out


def fractional_max_pool3d(x, output_size, kernel_size=None, random_u=None,
                          return_mask=False, name=None):
    if return_mask:
        raise NotImplementedError("return_mask unsupported on TPU path")
    if isinstance(output_size, int):
        output_size = (output_size,) * 3
    return _fractional_max_pool3d(x, tuple(output_size),
                                  _sample_u(random_u))


def _unpool(x, indices, spatial_out, ndim):
    # x, indices: (N, C, *spatial_in); indices flat into spatial_out
    n, c = x.shape[:2]
    flat_in = int(np.prod(x.shape[2:]))
    flat_out = int(np.prod(spatial_out))
    xi = x.reshape(n, c, flat_in)
    ii = indices.reshape(n, c, flat_in)
    out = jnp.zeros((n, c, flat_out), x.dtype)
    out = jax.vmap(jax.vmap(
        lambda o, idx, v: o.at[idx].set(v)))(out, ii, xi)
    return out.reshape((n, c) + tuple(spatial_out))


def _unpool_out_shape(in_sp, kernel_size, stride, padding, output_size, nd):
    if output_size is not None:
        return tuple(output_size)[-nd:]
    ks = kernel_size if isinstance(kernel_size, (list, tuple)) \
        else (kernel_size,) * nd
    st = stride if isinstance(stride, (list, tuple)) else \
        ((stride,) * nd if stride is not None else ks)
    pd = padding if isinstance(padding, (list, tuple)) else (padding,) * nd
    return tuple((i - 1) * s - 2 * p + k
                 for i, k, s, p in zip(in_sp, ks, st, pd))


def _make_unpool(name, nd):
    @defop(name)
    def op(x, indices, spatial_out):
        return _unpool(x, indices.astype(jnp.int32), spatial_out, nd)

    def api(x, indices, kernel_size, stride=None, padding=0,
            data_format="NCL" if nd == 1 else ("NCHW" if nd == 2
                                               else "NCDHW"),
            output_size=None, name=None):
        sp = _unpool_out_shape(tuple(x.shape[2:]), kernel_size, stride,
                               padding, output_size, nd)
        return op(x, _arr(indices), tuple(sp))
    api.__name__ = name
    return api


max_unpool1d = _make_unpool("max_unpool1d", 1)
max_unpool2d = _make_unpool("max_unpool2d", 2)
max_unpool3d = _make_unpool("max_unpool3d", 3)


# -- vision: affine_grid / grid_sample -------------------------------------

@defop("affine_grid")
def _affine_grid(theta, out_h, out_w, align_corners):
    n = theta.shape[0]
    if align_corners:
        ys = jnp.linspace(-1.0, 1.0, out_h)
        xs = jnp.linspace(-1.0, 1.0, out_w)
    else:
        ys = (jnp.arange(out_h) * 2 + 1) / out_h - 1
        xs = (jnp.arange(out_w) * 2 + 1) / out_w - 1
    gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
    ones = jnp.ones_like(gx)
    base = jnp.stack([gx, gy, ones], axis=-1)          # (H, W, 3)
    grid = jnp.einsum("hwk,nck->nhwc", base, theta)    # (N, H, W, 2)
    return grid


def affine_grid(theta, out_shape, align_corners=True, name=None):
    n, c, h, w = [int(s) for s in out_shape]
    return _affine_grid(theta, h, w, bool(align_corners))


@defop("grid_sample")
def _grid_sample(x, grid, mode, padding_mode, align_corners):
    # x: (N, C, H, W); grid: (N, Hg, Wg, 2) in [-1, 1] (x, y)
    n, c, h, w = x.shape
    gx, gy = grid[..., 0], grid[..., 1]
    if align_corners:
        fx = (gx + 1) * (w - 1) / 2
        fy = (gy + 1) * (h - 1) / 2
    else:
        fx = ((gx + 1) * w - 1) / 2
        fy = ((gy + 1) * h - 1) / 2

    if padding_mode == "reflection":
        def reflect(v, lo, hi):
            if hi <= lo:
                return jnp.zeros_like(v) + lo
            span = hi - lo
            v = jnp.abs((v - lo) % (2 * span))
            return jnp.minimum(v, 2 * span - v) + lo
        if align_corners:
            fx = reflect(fx, 0.0, w - 1.0)
            fy = reflect(fy, 0.0, h - 1.0)
        else:
            fx = reflect(fx, -0.5, w - 0.5)
            fy = reflect(fy, -0.5, h - 0.5)

    x0 = jnp.floor(fx).astype(jnp.int32)
    y0 = jnp.floor(fy).astype(jnp.int32)
    x1, y1 = x0 + 1, y0 + 1
    wx = fx - x0
    wy = fy - y0

    def gather(xi, yi):
        inb = (xi >= 0) & (xi < w) & (yi >= 0) & (yi < h)
        xi_c = jnp.clip(xi, 0, w - 1)
        yi_c = jnp.clip(yi, 0, h - 1)
        # (N, Hg, Wg) index into (N, C, H, W) -> (N, C, Hg, Wg)
        batch = jnp.arange(n).reshape(n, 1, 1)
        v = x[batch, :, yi_c, xi_c]                    # (N, Hg, Wg, C)
        v = jnp.moveaxis(v, -1, 1)
        if padding_mode == "zeros":
            v = v * inb[:, None, :, :]
        return v

    if mode == "nearest":
        xi = jnp.round(fx).astype(jnp.int32)
        yi = jnp.round(fy).astype(jnp.int32)
        return gather(xi, yi)
    v00 = gather(x0, y0)
    v01 = gather(x1, y0)
    v10 = gather(x0, y1)
    v11 = gather(x1, y1)
    wx_ = wx[:, None, :, :]
    wy_ = wy[:, None, :, :]
    return (v00 * (1 - wx_) * (1 - wy_) + v01 * wx_ * (1 - wy_)
            + v10 * (1 - wx_) * wy_ + v11 * wx_ * wy_)


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    """Bilinear/nearest sampler (reference: functional/vision.py
    grid_sample; CUDA kernel grid_sampler). XLA gathers ride the same
    fused path as embedding lookups on TPU."""
    return _grid_sample(x, grid, mode, padding_mode, bool(align_corners))


# -- losses ----------------------------------------------------------------

@defop("dice_loss")
def _dice_loss(input, label, epsilon):
    # input: (N, ..., C) probabilities, label: (N, ..., 1) int
    n = input.shape[0]
    c = input.shape[-1]
    lab = jax.nn.one_hot(label[..., 0], c, dtype=input.dtype)
    red = tuple(range(1, input.ndim))
    inter = jnp.sum(input * lab, axis=red)
    union = jnp.sum(input, axis=red) + jnp.sum(lab, axis=red)
    dice = (2 * inter + epsilon) / (union + epsilon)
    return jnp.mean(1 - dice)


def dice_loss(input, label, epsilon=1e-5, name=None):
    return _dice_loss(input, _arr(label), epsilon)


@defop("gaussian_nll_loss", amp_policy="black")
def _gaussian_nll(input, label, variance, full, epsilon, reduction):
    var = jnp.maximum(variance, epsilon)
    loss = 0.5 * (jnp.log(var) + (input - label) ** 2 / var)
    if full:
        loss = loss + 0.5 * math.log(2 * math.pi)
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


def gaussian_nll_loss(input, label, variance, full=False, epsilon=1e-6,
                      reduction="mean", name=None):
    return _gaussian_nll(input, label, variance, bool(full), epsilon,
                         reduction)


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    """Hierarchical sigmoid over the default complete binary tree
    (reference: functional/loss.py hsigmoid_loss; CPU kernel
    phi/kernels/cpu/hsigmoid_loss_kernel.cc — same default-tree coding)."""
    if path_table is not None or path_code is not None:
        raise NotImplementedError("custom trees not supported; use the "
                                  "default complete binary tree")
    lv = _arr(label).astype(jnp.int32)
    code_len = int(math.ceil(math.log2(max(num_classes, 2))))
    losses = _hsigmoid_op(input, weight, bias, lv, num_classes, code_len)
    from paddle_tpu import tensor as T
    return T.mean(losses)


@defop("hsigmoid_loss_op", amp_policy="black")
def _hsigmoid_op(x, w, b, lab, num_classes, code_len):
    """Walk leaf (lab + num_classes) up the complete binary tree; the
    walk STOPS at the root (node 1) — for non-power-of-two num_classes
    some classes have shorter codes, masked out via `live`."""
    total = jnp.zeros((x.shape[0],), jnp.float32)
    node = lab + num_classes
    for _ in range(code_len):
        parent = node // 2
        live = (node > 1).astype(jnp.float32)
        bit = (node % 2).astype(jnp.float32)               # code bit
        idx = jnp.clip(parent - 1, 0, num_classes - 1)
        logits = jnp.einsum("nd,nd->n", x.astype(jnp.float32),
                            w[idx].astype(jnp.float32))
        if b is not None:
            logits = logits + b.reshape(-1)[idx]
        # bit==1 -> sigmoid(-logit); standard hsigmoid BCE form
        total = total + live * (jax.nn.softplus(logits)
                                - (1 - bit) * logits)
        node = jnp.maximum(parent, 1)
    return total


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5,
                         margin3=0.0, scale=64.0, group=None,
                         return_softmax=False, reduction="mean", name=None):
    """ArcFace/CosFace margin loss (reference: functional/loss.py
    margin_cross_entropy; GPU kernel margin_cross_entropy_kernel.cu)."""
    return _margin_ce(logits, _arr(label), margin1, margin2, margin3,
                      scale, return_softmax, reduction)


@defop("margin_ce", amp_policy="black")
def _margin_ce(lg, lab, margin1, margin2, margin3, scale, return_softmax,
               reduction):
    lab = lab.astype(jnp.int32)
    theta = jnp.arccos(jnp.clip(lg, -1.0, 1.0))
    target = jnp.cos(margin1 * theta + margin2) - margin3
    onehot = jax.nn.one_hot(lab, lg.shape[-1], dtype=lg.dtype)
    adj = jnp.where(onehot > 0, target, lg) * scale
    logp = jax.nn.log_softmax(adj, axis=-1)
    nll = -jnp.take_along_axis(logp, lab[:, None], axis=-1)[:, 0]
    if reduction == "mean":
        loss = jnp.mean(nll)
    elif reduction == "sum":
        loss = jnp.sum(nll)
    else:
        loss = nll
    if return_softmax:
        return loss, jax.nn.softmax(adj, axis=-1)
    return loss


@defop("multi_label_soft_margin_loss")
def _mlsm(input, label, weight, reduction):
    loss = -(label * jax.nn.log_sigmoid(input)
             + (1 - label) * jax.nn.log_sigmoid(-input))
    if weight is not None:
        loss = loss * weight
    loss = jnp.mean(loss, axis=-1)
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


def multi_label_soft_margin_loss(input, label, weight=None,
                                 reduction="mean", name=None):
    return _mlsm(input, label, weight, reduction)


@defop("multi_margin_loss")
def _mml(input, label, p, margin, weight, reduction):
    n, c = input.shape
    lab = label.astype(jnp.int32)
    x_y = jnp.take_along_axis(input, lab[:, None], axis=-1)
    m = jnp.maximum(margin - x_y + input, 0.0) ** p
    if weight is not None:
        m = m * weight.reshape(-1)[lab][:, None]
    mask = 1.0 - jax.nn.one_hot(lab, c, dtype=input.dtype)
    loss = jnp.sum(m * mask, axis=-1) / c
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


def multi_margin_loss(input, label, p=1, margin=1.0, weight=None,
                      reduction="mean", name=None):
    return _mml(input, _arr(label), p, margin, weight, reduction)


@defop("npair_loss")
def _npair(anchor, positive, labels, l2_reg):
    sim = anchor @ positive.T                       # (N, N)
    lab = labels.reshape(-1)
    tgt = (lab[:, None] == lab[None, :]).astype(sim.dtype)
    tgt = tgt / jnp.sum(tgt, axis=-1, keepdims=True)
    logp = jax.nn.log_softmax(sim, axis=-1)
    ce = -jnp.mean(jnp.sum(tgt * logp, axis=-1))
    reg = l2_reg * (jnp.mean(jnp.sum(anchor * anchor, -1))
                    + jnp.mean(jnp.sum(positive * positive, -1))) / 4
    return ce + reg


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    return _npair(anchor, positive, _arr(labels), l2_reg)


@defop("pairwise_distance", amp_policy="black")
def _pairwise_distance(x, y, p, epsilon, keepdim):
    d = x - y + epsilon
    return jnp.power(jnp.sum(jnp.power(jnp.abs(d), p), axis=-1,
                             keepdims=keepdim), 1.0 / p)


def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False, name=None):
    return _pairwise_distance(x, y, p, epsilon, bool(keepdim))


@defop("poisson_nll_loss", amp_policy="black")
def _poisson_nll(input, label, log_input, full, epsilon, reduction):
    if log_input:
        loss = jnp.exp(input) - label * input
    else:
        loss = input - label * jnp.log(input + epsilon)
    if full:
        stirling = (label * jnp.log(label + 1e-30) - label
                    + 0.5 * jnp.log(2 * math.pi * jnp.maximum(label, 1e-30)))
        loss = loss + jnp.where(label > 1, stirling, 0.0)
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


def poisson_nll_loss(input, label, log_input=True, full=False,
                     epsilon=1e-8, reduction="mean", name=None):
    return _poisson_nll(input, label, bool(log_input), bool(full), epsilon,
                        reduction)


@defop("soft_margin_loss")
def _soft_margin(input, label, reduction):
    loss = jax.nn.softplus(-label * input)
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


def soft_margin_loss(input, label, reduction="mean", name=None):
    return _soft_margin(input, label, reduction)


def triplet_margin_with_distance_loss(input, positive, negative,
                                      distance_function=None, margin=1.0,
                                      swap=False, reduction="mean",
                                      name=None):
    """(reference: functional/loss.py triplet_margin_with_distance_loss)."""
    from paddle_tpu import tensor as T
    dist = distance_function or (lambda a, b: pairwise_distance(a, b))
    d_pos = dist(input, positive)
    d_neg = dist(input, negative)
    if swap:
        d_pn = dist(positive, negative)
        d_neg = T.minimum(d_neg, d_pn)
    loss = T.clip(d_pos - d_neg + margin, min=0.0)
    if reduction == "mean":
        return T.mean(loss)
    if reduction == "sum":
        return T.sum(loss)
    return loss


@defop("rnnt_loss", amp_policy="black")
def _rnnt_loss(logits, labels, logit_lengths, label_lengths, blank,
               fastemit_lambda):
    """RNN-Transducer loss (reference: python/paddle/nn/functional/loss.py
    rnnt_loss over third_party/warprnnt). TPU-native: the alpha-lattice
    dynamic program as a lax.scan over time; each step updates the whole
    label axis vectorized — no per-cell kernel needed.
    logits: (B, T, U+1, V) raw; labels: (B, U) int."""
    b, t_max, u_max1, v = logits.shape
    u_max = u_max1 - 1
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    lab = labels.astype(jnp.int32)
    # per (b,t,u): blank prob and emit prob of the next label
    p_blank = logp[:, :, :, blank]                        # (B, T, U+1)
    lab_pad = jnp.concatenate(
        [lab, jnp.zeros((b, 1), jnp.int32)], axis=1)      # (B, U+1)
    p_emit = jnp.take_along_axis(
        logp, lab_pad[:, None, :, None], axis=-1)[..., 0]  # (B, T, U+1)
    if fastemit_lambda:
        # FastEmit: scale emission probability mass by (1 + lambda) so
        # early-emitting paths are favored (warprnnt applies the same
        # (1+lambda) factor on the emit arcs)
        p_emit = p_emit + math.log1p(fastemit_lambda)

    NEG = -1e30

    # alpha recursion (time outer scan, label inner scan):
    #   alpha[t,u] = logsumexp(alpha[t-1,u] + blank(t-1,u),
    #                          alpha[t,u-1] + emit(t,u-1))
    def time_step(alpha, t):
        from_blank = alpha + p_blank[:, t - 1, :]          # (B, U+1)

        def label_scan(carry, u):
            left = carry
            cur = jnp.where(
                u == 0, from_blank[:, 0],
                jnp.logaddexp(from_blank[:, u],
                              left + p_emit[:, t, u - 1]))
            return cur, cur
        _, cols = jax.lax.scan(label_scan, jnp.full((b,), NEG),
                               jnp.arange(u_max1))
        new_alpha = jnp.swapaxes(cols, 0, 1)
        active = (t < logit_lengths)[:, None]
        return jnp.where(active, new_alpha, alpha), None

    # t = 0 row: only emits
    def init_scan(carry, u):
        left = carry
        cur = jnp.where(u == 0, 0.0, left + p_emit[:, 0, u - 1])
        return cur, cur
    _, cols0 = jax.lax.scan(init_scan, jnp.zeros((b,)), jnp.arange(u_max1))
    alpha = jnp.swapaxes(cols0, 0, 1)

    alpha, _ = jax.lax.scan(time_step, alpha, jnp.arange(1, t_max))
    # total log prob: alpha[T-1, U] + blank(T-1, U)
    t_last = jnp.clip(logit_lengths - 1, 0, t_max - 1)
    bidx = jnp.arange(b)
    final = (alpha[bidx, label_lengths]
             + p_blank[bidx, t_last, label_lengths])
    return -final


def rnnt_loss(input, label, input_lengths, label_lengths, blank=0,
              fastemit_lambda=0.001, reduction="mean", name=None):
    out = _rnnt_loss(input, _arr(label), _arr(input_lengths),
                     _arr(label_lengths), int(blank), fastemit_lambda)
    from paddle_tpu import tensor as T
    if reduction == "mean":
        return T.mean(out)
    if reduction == "sum":
        return T.sum(out)
    return out


def sparse_attention(query, key, value, sparse_csr_offset,
                     sparse_csr_columns, key_padding_mask=None,
                     attn_mask=None, name=None):
    """(reference: functional/sparse_attention.py — CUDA block-sparse
    kernel). Routes to the CSR-pattern attention in paddle.sparse."""
    from paddle_tpu import sparse
    b, h = query.shape[0], query.shape[1]
    outs = []
    from paddle_tpu import tensor as T
    for bi in range(b):
        for hi in range(h):
            q = query[bi, hi]
            k = key[bi, hi]
            v = value[bi, hi]
            crows = _arr(sparse_csr_offset)[bi, hi]
            cols = _arr(sparse_csr_columns)[bi, hi]
            mask = sparse.sparse_csr_tensor(
                crows, cols, jnp.ones((cols.shape[0],), jnp.float32),
                (q.shape[0], k.shape[0]))
            outs.append(sparse.nn.functional.attention(q, k, v, mask))
    out = T.stack(outs, 0)
    return T.reshape(out, [b, h] + list(out.shape[1:]))


def zeropad2d(x, padding, data_format="NCHW", name=None):
    from paddle_tpu.nn import functional as F
    return F.pad(x, padding, mode="constant", value=0.0,
                 data_format=data_format)


@defop("gather_tree", differentiable=False)
def _gather_tree(ids, parents):
    # ids, parents: (max_time, batch, beam)
    t_max = ids.shape[0]

    def back(carry, t):
        beams = carry                                    # (batch, beam)
        step_ids = jnp.take_along_axis(ids[t], beams, axis=-1)
        next_beams = jnp.take_along_axis(parents[t], beams, axis=-1)
        return next_beams, step_ids
    init = jnp.broadcast_to(jnp.arange(ids.shape[2]),
                            ids.shape[1:]).astype(ids.dtype)
    _, out = jax.lax.scan(back, init, jnp.arange(t_max), reverse=True)
    return out


def gather_tree(ids, parents):
    """Beam-search ancestry resolution (reference: functional/common
    gather_tree op)."""
    return _gather_tree(_arr(ids), _arr(parents))


def class_center_sample(label, num_classes, num_samples, group=None):
    """Sample negative class centers union positive ones (reference:
    functional/common.py class_center_sample — PartialFC training).
    Returns (remapped_label, sampled_class_index)."""
    lab = np.asarray(_arr(label)).ravel()
    pos = np.unique(lab)
    if len(pos) >= num_samples:
        sampled = pos
    else:
        neg_pool = np.setdiff1d(np.arange(num_classes), pos)
        rng = np.random.RandomState()
        extra = rng.choice(neg_pool, size=num_samples - len(pos),
                           replace=False)
        sampled = np.sort(np.concatenate([pos, extra]))
    remap = -np.ones(num_classes, np.int64)
    remap[sampled] = np.arange(len(sampled))
    return (Tensor(jnp.asarray(remap[lab].astype(np.int32))),
            Tensor(jnp.asarray(sampled.astype(np.int32))))


def _act_inplace(fn):
    def api(x, *a, **k):
        return x._inplace_assign(fn(x, *a, **k))
    return api


def _late_bind_inplace():
    # bound late: activation module is part of the same package import
    from paddle_tpu.nn.functional import activation as A
    globals()["hardtanh_"] = _act_inplace(A.hardtanh)
    globals()["leaky_relu_"] = _act_inplace(A.leaky_relu)
    globals()["tanh_"] = _act_inplace(A.tanh)
    globals()["thresholded_relu_"] = _act_inplace(A.thresholded_relu)
    __all__.extend(["hardtanh_", "leaky_relu_", "tanh_",
                    "thresholded_relu_"])


_late_bind_inplace()
