"""Attention ops.

Reference surface: paddle.nn.functional.scaled_dot_product_attention +
flash_attention (reference: python/paddle/nn/functional/flash_attention.py,
kernels at phi/kernels/gpu/flash_attn_kernel.cu wrapping the vendored FA2
library). TPU-native: the default path is an XLA-fused SDPA; the Pallas
flash kernel (paddle_tpu.kernels.flash_attention) is used for long
sequences, where materializing the (S, S) score matrix would blow HBM.

Layout note: paddle flash_attention takes (batch, seqlen, num_heads,
head_dim) — kept here.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from paddle_tpu.core.dispatch import defop


def _sdpa_ref(q, k, v, attn_mask=None, dropout_p=0.0, is_causal=False,
              scale=None):
    # q,k,v: (B, S, H, D) -> compute in (B, H, S, D)
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    d = q.shape[-1]
    s = scale if scale is not None else 1.0 / math.sqrt(d)
    # GQA: repeat kv heads if fewer than q heads
    hq, hk = qt.shape[1], kt.shape[1]
    if hk != hq:
        rep = hq // hk
        kt = jnp.repeat(kt, rep, axis=1)
        vt = jnp.repeat(vt, rep, axis=1)
    scores = jnp.einsum("bhqd,bhkd->bhqk", qt, kt,
                        preferred_element_type=jnp.float32) * s
    if is_causal:
        ql, kl = scores.shape[-2], scores.shape[-1]
        mask = jnp.tril(jnp.ones((ql, kl), bool), k=kl - ql)
        scores = jnp.where(mask, scores, -jnp.inf)
    if attn_mask is not None:
        if attn_mask.dtype == jnp.bool_:
            scores = jnp.where(attn_mask, scores, -jnp.inf)
        else:
            scores = scores + attn_mask.astype(scores.dtype)
    probs = jax.nn.softmax(scores, axis=-1).astype(qt.dtype)
    if is_causal or attn_mask is not None:
        # a fully-masked query row (e.g. a left-padded position under a
        # padding mask) softmaxes all -inf to NaN; emit 0 instead, the
        # flash-kernel convention — NaN here would poison downstream
        # residuals and any KV cache written from them
        all_masked = jnp.isneginf(scores).all(-1, keepdims=True)
        probs = jnp.where(all_masked, 0.0, probs).astype(qt.dtype)
    if dropout_p:
        # layers gate on self.training before passing dropout_p; under jit
        # the key is baked at trace time (fixed mask per compile), matching
        # the reference's seeded static-graph dropout
        from paddle_tpu.core.random import next_key
        keep = 1.0 - dropout_p
        dmask = jax.random.bernoulli(next_key(), keep, probs.shape)
        probs = jnp.where(dmask, probs / keep, 0.0).astype(qt.dtype)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, vt)
    return jnp.swapaxes(out, 1, 2)


@defop("scaled_dot_product_attention", amp_policy="white",
       spmd_note="heads shard over 'mp'; seq shards need ring attention "
                 "(paddle_tpu.distributed.ring_attention)")
def _sdpa(q, k, v, attn_mask=None, dropout_p=0.0, is_causal=False,
          scale=None):
    from paddle_tpu.distributed.context_parallel import (
        current_context_parallel, dispatch_context_parallel)
    if (current_context_parallel() and attn_mask is None and is_causal
            and scale is None):
        return dispatch_context_parallel(q, k, v, True)
    return _sdpa_ref(q, k, v, attn_mask, dropout_p, is_causal, scale)


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False,
                                 training=True, name=None):
    return _sdpa(query, key, value, attn_mask, dropout_p=dropout_p,
                 is_causal=is_causal)


@defop("flash_attention_op", amp_policy="white")
def _flash_attention(q, k, v, dropout=0.0, causal=False):
    from paddle_tpu.distributed.context_parallel import (
        current_context_parallel, dispatch_context_parallel)
    from paddle_tpu.kernels import flash_attention as fa
    if current_context_parallel() and causal:
        return dispatch_context_parallel(q, k, v, True)
    return fa.flash_attention_bshd(q, k, v, causal=causal)


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, fixed_seed_offset=None,
                    rng_name="", training=True, name=None):
    """Reference: python/paddle/nn/functional/flash_attention.py
    flash_attention. Returns (out, softmax_lse-placeholder) like the
    reference's (out, softmax) pair."""
    out = _flash_attention(query, key, value, dropout=dropout,
                           causal=causal)
    if return_softmax:
        return out, None
    return out, None


def flash_attn_unpadded(*args, **kwargs):
    raise NotImplementedError(
        "varlen flash attention: use dense batches + masks on TPU (static "
        "shapes); ragged support arrives with the Pallas splash kernel")
