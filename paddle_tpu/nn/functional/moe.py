"""MoE gating + dispatch, TPU-native.

Reference: python/paddle/incubate/distributed/models/moe/ — MoELayer with
gshard/switch/naive gates (gate/gshard_gate.py, switch_gate.py) dispatching
tokens through MoEScatter/MoEGather PyLayers over the global_scatter /
global_gather all-to-all collective ops
(paddle/fluid/operators/collective/global_scatter_op.cc).

TPU-native: the GShard dense-einsum formulation. Gating produces a combine
tensor (T, E, C) and a boolean dispatch mask; dispatch/return are einsums.
When expert weights are sharded over the mesh's 'ep' axis, XLA partitions
the (E, C, D) expert batch over 'ep' and emits the token all-to-all over
ICI itself — the reference's global_scatter/global_gather pair compiled
from shardings instead of hand-written. Capacity is static (XLA needs
static shapes); overflow tokens are dropped (GShard semantics), which the
aux load-balancing loss drives towards zero.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

# version-safe axis_size (the bare jax.lax spelling is version-fragile;
# callers wrapping the ep-local entry points in shard_map should import
# it from paddle_tpu.core.jax_compat too)
from paddle_tpu.core.jax_compat import axis_size


def _one_hot(x, n, dtype=jnp.float32):
    return jax.nn.one_hot(x, n, dtype=dtype)


def top2_gating(logits, capacity_factor=1.25, train=True, rng_key=None):
    """GShard top-2 gating (reference: moe/gate/gshard_gate.py).

    logits: (T, E). Returns (combine (T,E,C), dispatch bool (T,E,C),
    aux_loss scalar)."""
    t, e = logits.shape
    c = max(4, int(math.ceil(2 * t * capacity_factor / e)))
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)

    idx1 = jnp.argmax(probs, axis=-1)                       # (T,)
    mask1 = _one_hot(idx1, e)
    probs2 = probs * (1.0 - mask1)
    idx2 = jnp.argmax(probs2, axis=-1)
    mask2 = _one_hot(idx2, e)

    # load-balancing aux loss (GShard eq.: E * sum(me * ce))
    me = jnp.mean(probs, axis=0)
    ce = jnp.mean(mask1, axis=0)
    aux_loss = e * jnp.sum(me * ce)

    # positions within each expert's capacity buffer
    pos1 = (jnp.cumsum(mask1, axis=0) - 1.0) * mask1        # (T,E)
    pos2 = ((jnp.cumsum(mask2, axis=0) - 1.0)
            + jnp.sum(mask1, axis=0, keepdims=True)) * mask2
    keep1 = (pos1 < c) & (mask1 > 0)
    keep2 = (pos2 < c) & (mask2 > 0)
    mask1 = mask1 * keep1
    mask2 = mask2 * keep2

    g1 = jnp.sum(probs * mask1, axis=-1)                    # (T,)
    g2 = jnp.sum(probs * mask2, axis=-1)
    denom = jnp.maximum(g1 + g2, 1e-9)
    g1, g2 = g1 / denom, g2 / denom

    p1 = jnp.sum(pos1 * mask1, axis=-1).astype(jnp.int32)   # (T,)
    p2 = jnp.sum(pos2 * mask2, axis=-1).astype(jnp.int32)
    in1 = jnp.sum(mask1, axis=-1) > 0
    in2 = jnp.sum(mask2, axis=-1) > 0

    cap1 = _one_hot(p1, c) * in1[:, None]                   # (T,C)
    cap2 = _one_hot(p2, c) * in2[:, None]
    combine = (g1[:, None, None] * mask1[:, :, None] * cap1[:, None, :]
               + g2[:, None, None] * mask2[:, :, None] * cap2[:, None, :])
    dispatch = combine > 0
    return combine, dispatch, aux_loss


def switch_gating(logits, capacity_factor=1.25, train=True, rng_key=None):
    """Switch-Transformer top-1 gating (reference: moe/gate/switch_gate.py),
    with optional multiplicative jitter during training."""
    t, e = logits.shape
    c = max(4, int(math.ceil(t * capacity_factor / e)))
    if train and rng_key is not None:
        noise = jax.random.uniform(rng_key, logits.shape, jnp.float32,
                                   0.98, 1.02)
        logits = logits * noise
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    idx = jnp.argmax(probs, axis=-1)
    mask = _one_hot(idx, e)

    me = jnp.mean(probs, axis=0)
    ce = jnp.mean(mask, axis=0)
    aux_loss = e * jnp.sum(me * ce)

    pos = (jnp.cumsum(mask, axis=0) - 1.0) * mask
    keep = (pos < c) & (mask > 0)
    mask = mask * keep
    gate = jnp.sum(probs * mask, axis=-1)
    p = jnp.sum(pos * mask, axis=-1).astype(jnp.int32)
    inc = jnp.sum(mask, axis=-1) > 0
    cap = _one_hot(p, c) * inc[:, None]
    combine = gate[:, None, None] * mask[:, :, None] * cap[:, None, :]
    return combine, combine > 0, aux_loss


def moe_dispatch(x, dispatch):
    """x (T,D), dispatch (T,E,C) -> expert inputs (E,C,D)."""
    return jnp.einsum("tec,td->ecd", dispatch.astype(x.dtype), x)


def moe_combine(expert_out, combine):
    """expert_out (E,C,D), combine (T,E,C) -> (T,D)."""
    return jnp.einsum("tec,ecd->td", combine.astype(expert_out.dtype),
                      expert_out)


def topk_gating_dropless(logits, k):
    """Dropless top-k gating (MegaBlocks/dMoE semantics; the reference's
    gshard gate at moe/gate/gshard_gate.py drops at capacity — this path
    never drops): every token's top-k experts are honored exactly.

    logits (T, E) -> (expert_idx (T,k) int32, gates (T,k) f32
    renormalized over the top-k, aux_loss scalar). The aux loss keeps
    the GShard form (E * sum(me * ce)) with ce = mean assignment
    fraction over all T*k slots — load balance still matters for
    grouped-matmul efficiency even though nothing is dropped."""
    t, e = logits.shape
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    gates, idx = jax.lax.top_k(probs, k)                    # (T, k)
    gates = gates / jnp.maximum(
        jnp.sum(gates, axis=-1, keepdims=True), 1e-9)
    me = jnp.mean(probs, axis=0)
    ce = jnp.mean(jnp.sum(_one_hot(idx, e), axis=1), axis=0) / k
    aux_loss = e * jnp.sum(me * ce)
    return idx.astype(jnp.int32), gates, aux_loss


def moe_dropless_mlp_ep_local(xt, router_w, wg, wu, wd, k, axis_name,
                              token_axes=(), buffer_rows=None):
    """Expert-parallel dropless dMoE — the per-shard body (runs inside
    shard_map over the `axis_name` ('ep') mesh axis).

    Reference mechanism: global_scatter / global_gather all-to-all
    (python/paddle/distributed/utils/moe_utils.py:20,
    incubate/distributed/models/moe/moe_layer.py:263). TPU-native
    realisation: the ragged (token, expert) pair stream is packed into a
    DENSE-PADDED per-destination buffer and exchanged with
    `lax.all_to_all` (XLA's ragged-all-to-all is not available on every
    backend; dense padding keeps shapes static, which XLA needs anyway).

    xt: (T_local, D) this shard's tokens. router_w: (D, E) replicated.
    wg/wu: (E_local, D, F), wd: (E_local, F, D) — expert dim already
    sharded over `axis_name`. Tokens route by global expert id; shard p
    owns experts [p*E_local, (p+1)*E_local).

    buffer_rows: per-(src, dst) buffer capacity. None (default) =
    T_local*k — the worst case, so NOTHING is ever dropped (true
    dropless at P x memory in the a2a buffers). Smaller values trade
    memory/compute for GShard-style overflow drops (overflowing pairs
    contribute zero, gates NOT renormalized — monitor aux_loss).

    Returns (out (T_local, D), aux_loss scalar pmean'd over
    token_axes + (axis_name,))."""
    t_l, d = xt.shape
    e_l = wg.shape[0]
    p = axis_size(axis_name)
    me = jax.lax.axis_index(axis_name)
    e = e_l * p
    n = t_l * k
    cbuf = n if buffer_rows is None else int(buffer_rows)

    logits = jnp.einsum("td,de->te", xt.astype(jnp.float32),
                        router_w.astype(jnp.float32))
    probs = jax.nn.softmax(logits, axis=-1)
    gates, idx = jax.lax.top_k(probs, k)                     # (T_l, k)
    gates = gates / jnp.maximum(
        jnp.sum(gates, axis=-1, keepdims=True), 1e-9)
    # aux loss over GLOBAL token means (reference computes it on the
    # full batch; local means pmean'd are exact for equal shard sizes)
    red = tuple(token_axes) + (axis_name,)
    me_mean = jax.lax.pmean(jnp.mean(probs, axis=0), red)
    ce_mean = jax.lax.pmean(
        jnp.mean(jnp.sum(_one_hot(idx, e), axis=1), axis=0) / k, red)
    aux = e * jnp.sum(me_mean * ce_mean)

    # ---- pack: sort pairs by global expert id (= by destination, and
    # by expert within destination) into (P, cbuf, D) send buffers ----
    flat_e = idx.reshape(-1).astype(jnp.int32)               # (N,)
    order = jnp.argsort(flat_e, stable=True)
    sorted_e = jnp.take(flat_e, order)
    sorted_x = jnp.take(xt, order // k, axis=0)              # (N, D)
    dest = sorted_e // e_l                                   # (N,)
    send_counts = jnp.bincount(dest, length=p)
    start = jnp.cumsum(send_counts) - send_counts            # excl. cumsum
    slot = jnp.arange(n, dtype=jnp.int32) - start[dest].astype(jnp.int32)
    send_x = jnp.zeros((p, cbuf, d), xt.dtype).at[dest, slot].set(
        sorted_x, mode="drop")
    send_e = jnp.full((p, cbuf), e, jnp.int32).at[dest, slot].set(
        sorted_e, mode="drop")                               # e = sentinel

    # ---- all-to-all: row block i of the buffer goes to shard i ------
    a2a = lambda a: jax.lax.all_to_all(                      # noqa: E731
        a, axis_name, split_axis=0, concat_axis=0, tiled=True)
    recv_x = a2a(send_x).reshape(p * cbuf, d)
    recv_e = a2a(send_e).reshape(p * cbuf)

    # ---- local ragged grouped matmul over MY experts ----------------
    # received ids are all in [me*e_l, (me+1)*e_l) or the sentinel;
    # sort groups them, the sentinel rows form a trailing junk group
    # consumed by a zero dummy expert so group sizes sum to the row
    # count (lax.ragged_dot contract)
    order2 = jnp.argsort(recv_e, stable=True)
    rx = jnp.take(recv_x, order2, axis=0)
    le = jnp.take(recv_e, order2) - me * e_l
    le = jnp.where(le < e_l, le, e_l).astype(jnp.int32)
    group_sizes = jnp.bincount(le, length=e_l + 1).astype(jnp.int32)
    pad = lambda w: jnp.concatenate(                         # noqa: E731
        [w, jnp.zeros((1,) + w.shape[1:], w.dtype)], axis=0)
    a = jax.lax.ragged_dot(rx, pad(wg).astype(rx.dtype), group_sizes)
    b_up = jax.lax.ragged_dot(rx, pad(wu).astype(rx.dtype), group_sizes)
    act = jax.nn.silu(a.astype(jnp.float32)).astype(rx.dtype) * b_up
    o = jax.lax.ragged_dot(act, pad(wd).astype(rx.dtype), group_sizes)
    inv2 = jnp.argsort(order2, stable=True)
    out_recv = jnp.take(o, inv2, axis=0).reshape(p, cbuf, d)

    # ---- return trip + unpack ---------------------------------------
    back = a2a(out_recv)                                     # (P,cbuf,D)
    val_sorted = back[dest, jnp.clip(slot, 0, cbuf - 1)]
    val_sorted = jnp.where((slot < cbuf)[:, None], val_sorted, 0.0)
    inv = jnp.argsort(order, stable=True)
    out_rows = jnp.take(val_sorted, inv, axis=0).reshape(t_l, k, d)
    out = jnp.sum(gates[..., None].astype(xt.dtype) * out_rows, axis=1)
    return out, aux


def moe_dropless_mlp(xt, wg, wu, wd, idx, gates):
    """Sort-based grouped-matmul expert MLP with ZERO token drops
    (MegaBlocks-style; TPU-native via jax.lax.ragged_dot — the
    XLA grouped matmul MaxText uses for dMoE).

    xt (T, D); wg/wu (E, D, F); wd (E, F, D); idx/gates (T, k).
    All shapes static: the T*k (token, expert) pairs are sorted by
    expert id, each expert consumes a contiguous ragged row-group, and
    outputs unsort back to token order. -> (T, D)."""
    t, d = xt.shape
    e = wg.shape[0]
    k = idx.shape[1]
    flat_e = idx.reshape(-1)                                # (T*k,)
    order = jnp.argsort(flat_e, stable=True)
    tok_of = order // k
    sorted_x = jnp.take(xt, tok_of, axis=0)                 # (T*k, D)
    group_sizes = jnp.bincount(flat_e, length=e).astype(jnp.int32)
    a = jax.lax.ragged_dot(sorted_x, wg.astype(xt.dtype), group_sizes)
    b = jax.lax.ragged_dot(sorted_x, wu.astype(xt.dtype), group_sizes)
    act = jax.nn.silu(a.astype(jnp.float32)).astype(xt.dtype) * b
    o = jax.lax.ragged_dot(act, wd.astype(xt.dtype), group_sizes)
    inv = jnp.argsort(order, stable=True)
    out_rows = jnp.take(o, inv, axis=0).reshape(t, k, d)
    return jnp.sum(gates[..., None].astype(xt.dtype) * out_rows, axis=1)
