"""Common NN functional ops (reference: python/paddle/nn/functional/common.py,
input.py, extension.py)."""
from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.core.dispatch import defop
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.core.random import next_key
from paddle_tpu.core import dtype as dtypes


@defop("linear", amp_policy="white",
       spmd_note="weight (in,out): shard out over 'mp' for column-parallel, "
                 "in for row-parallel (reference: fleet/layers/mpu/mp_layers.py)")
def _linear(x, weight, bias=None):
    out = jnp.matmul(x, weight)
    if bias is not None:
        out = out + bias
    return out


def linear(x, weight, bias=None, name=None):
    return _linear(x, weight, bias)


@defop("embedding_op",
       spmd_note="vocab-sharded embedding = gather + psum over 'mp' "
                 "(reference: c_embedding_kernel)")
def _embedding(x, weight, padding_idx=None):
    out = _vocab_take(weight, x)
    if padding_idx is not None:
        mask = (x != padding_idx)[..., None]
        out = out * mask.astype(out.dtype)
    return out


def _ambient_mesh():
    """The device mesh visible at trace time. The jax mesh-context stack
    wins (the Trainer enters ITS mesh around step dispatch/lowering so
    sharding-aware vjps see the mesh the traced arrays actually live on);
    the paddle_tpu global ProcessMesh (set_mesh/fleet.init) is only a
    fallback — it may describe a different mesh than the trainer's."""
    try:
        from jax._src.mesh import thread_resources
        m = thread_resources.env.physical_mesh
        if not m.empty:
            return m
    except Exception:  # lint: disable=silent-swallow -- jax-internal mesh probe; the paddle_tpu global mesh fallback follows
        pass
    from paddle_tpu.distributed.mesh import get_mesh
    return getattr(get_mesh(), "jax_mesh", None)


def _vocab_take(weight, x):
    return _vocab_take_op(weight.shape, str(weight.dtype))(weight, x)


@functools.lru_cache(maxsize=None)
def _vocab_take_op(wshape, wdtype):
    """jnp.take(weight, x, 0) with a sharding-aware backward.

    The vjp is the standard scatter-add, but when the active mesh has an
    'fsdp' axis the cotangent is resharded FIRST in two cheap steps —
    (1) all-gather 'fsdp' off the batch dim, (2) free slice of the now-
    replicated hidden dim onto 'fsdp'. The plan shards embedding tables
    (vocab:'mp', hidden:'fsdp'); without this, GSPMD must move 'fsdp'
    from the updates' batch tile to their hidden tile in one step, which
    it can only do by FULL rematerialization (replicate-then-repartition
    over all mesh axes — the '[SPMD] Involuntary full rematerialization'
    warning; real HBM+ICI traffic at scale)."""

    @jax.custom_vjp
    def take(weight, x):
        return jnp.take(weight, x, axis=0)

    def fwd(weight, x):
        return jnp.take(weight, x, axis=0), x

    def bwd(x, g):
        mesh = _ambient_mesh()
        if (mesh is not None and "fsdp" in mesh.axis_names
                and g.ndim >= 2 and wshape[-1] % mesh.shape["fsdp"] == 0):
            from jax.sharding import NamedSharding, PartitionSpec as P
            dp = "dp" if "dp" in mesh.axis_names else None
            # keep the 'sp' seq sharding through both steps: dropping it
            # would all-gather the whole cotangent over 'sp' in
            # context-parallel runs
            sp = ("sp" if ("sp" in mesh.axis_names and g.ndim >= 3)
                  else None)
            mid = (sp,) + (None,) * (g.ndim - 3) if g.ndim >= 3 else ()
            batch = P(dp, *mid, None)
            hid = P(dp, *mid, "fsdp")
            g = jax.lax.with_sharding_constraint(
                g, NamedSharding(mesh, batch))
            g = jax.lax.with_sharding_constraint(
                g, NamedSharding(mesh, hid))
        dW = jnp.zeros(wshape, g.dtype).at[x].add(g)
        return dW.astype(wdtype), None

    take.defvjp(fwd, bwd)
    return take


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    return _embedding(x, weight, padding_idx=padding_idx)


@defop("one_hot_op", differentiable=False)
def _one_hot(x, num_classes):
    return jax.nn.one_hot(x, num_classes, dtype=jnp.float32)


def one_hot(x, num_classes, name=None):
    return _one_hot(x, num_classes=int(num_classes))


@defop("dropout_op")
def _dropout(x, key, p=0.5, training=True, mode="upscale_in_train"):
    if not training or p == 0.0:
        return x
    keep = 1.0 - p
    mask = jax.random.bernoulli(key, keep, x.shape)
    if mode == "upscale_in_train":
        return jnp.where(mask, x / keep, 0.0).astype(x.dtype)
    return jnp.where(mask, x, 0.0).astype(x.dtype)


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train",
            name=None):
    if not training or p == 0.0:
        return x
    if axis is not None:
        return _dropout_axis(x, next_key(), p=p,
                             axis=tuple(axis) if isinstance(axis, (list, tuple))
                             else (axis,), mode=mode)
    return _dropout(x, next_key(), p=p, training=training, mode=mode)


@defop("dropout_axis")
def _dropout_axis(x, key, p=0.5, axis=(0,), mode="upscale_in_train"):
    keep = 1.0 - p
    mask_shape = tuple(s if i in axis else 1 for i, s in enumerate(x.shape))
    mask = jax.random.bernoulli(key, keep, mask_shape)
    if mode == "upscale_in_train":
        return jnp.where(mask, x / keep, 0.0).astype(x.dtype)
    return jnp.where(mask, x, 0.0).astype(x.dtype)


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    if not training or p == 0.0:
        return x
    ax = (0, 1) if data_format == "NCHW" else (0, 3)
    return _dropout_axis(x, next_key(), p=p, axis=ax)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    if not training or p == 0.0:
        return x
    ax = (0, 1) if data_format == "NCDHW" else (0, 4)
    return _dropout_axis(x, next_key(), p=p, axis=ax)


def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0.0:
        return x
    return _alpha_dropout(x, next_key(), p=p)


@defop("alpha_dropout_op")
def _alpha_dropout(x, key, p=0.5):
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale
    keep = 1.0 - p
    a = (keep + alpha_p ** 2 * keep * (1 - keep)) ** -0.5
    b = -a * alpha_p * (1 - keep)
    mask = jax.random.bernoulli(key, keep, x.shape)
    return (a * jnp.where(mask, x, alpha_p) + b).astype(x.dtype)


@defop("normalize_op")
def _normalize(x, p=2, axis=1, epsilon=1e-12):
    norm = jnp.power(jnp.sum(jnp.power(jnp.abs(x), p), axis=axis,
                             keepdims=True), 1.0 / p)
    return x / jnp.maximum(norm, epsilon)


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    return _normalize(x, p=p, axis=axis, epsilon=epsilon)


@defop("cosine_similarity")
def _cosine_similarity(x1, x2, axis=1, eps=1e-8):
    dot = jnp.sum(x1 * x2, axis=axis)
    n1 = jnp.sqrt(jnp.sum(x1 * x1, axis=axis))
    n2 = jnp.sqrt(jnp.sum(x2 * x2, axis=axis))
    return dot / jnp.maximum(n1 * n2, eps)


def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    return _cosine_similarity(x1, x2, axis=axis, eps=eps)


@defop("bilinear_op", amp_policy="white")
def _bilinear(x1, x2, weight, bias=None):
    # weight: (out_features, in1, in2)
    out = jnp.einsum("bi,oij,bj->bo", x1, weight, x2)
    if bias is not None:
        out = out + bias
    return out


def bilinear(x1, x2, weight, bias=None, name=None):
    return _bilinear(x1, x2, weight, bias)


# ---------------------------------------------------------------------------
# interpolate / upsample
# ---------------------------------------------------------------------------
@defop("interpolate_op")
def _interpolate(x, size, mode="nearest", align_corners=False,
                 data_format="NCHW"):
    # normalize to channel-last for jax.image, then back
    if data_format in ("NCHW", "NCDHW", "NCW"):
        spatial = x.shape[2:]
        perm_in = (0,) + tuple(range(2, x.ndim)) + (1,)
        xi = jnp.transpose(x, perm_in)
    else:
        spatial = x.shape[1:-1]
        xi = x
    jmode = {"nearest": "nearest", "bilinear": "linear", "linear": "linear",
             "trilinear": "linear", "bicubic": "cubic", "area": "linear"}[mode]
    out_shape = (xi.shape[0],) + tuple(size) + (xi.shape[-1],)
    out = jax.image.resize(xi.astype(jnp.float32), out_shape, method=jmode
                           ).astype(x.dtype)
    if data_format in ("NCHW", "NCDHW", "NCW"):
        nd = out.ndim
        perm_out = (0, nd - 1) + tuple(range(1, nd - 1))
        out = jnp.transpose(out, perm_out)
    return out


def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format="NCHW",
                name=None):
    nd = x.ndim - 2
    if size is None:
        if scale_factor is None:
            raise ValueError("one of size / scale_factor required")
        sf = scale_factor if isinstance(scale_factor, (list, tuple)) \
            else [scale_factor] * nd
        spatial = x.shape[2:] if data_format.startswith("NC") else x.shape[1:-1]
        size = [int(s * f) for s, f in zip(spatial, sf)]
    size = [int(s.item()) if isinstance(s, Tensor) else int(s) for s in
            (size if isinstance(size, (list, tuple)) else [size] * nd)]
    return _interpolate(x, size=tuple(size), mode=mode,
                        align_corners=align_corners, data_format=data_format)


def upsample(x, size=None, scale_factor=None, mode="nearest",
             align_corners=False, align_mode=0, data_format="NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners,
                       align_mode, data_format)


@defop("pixel_shuffle")
def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    if data_format not in ("NCHW", "NHWC"):
        raise ValueError(f"data_format must be NCHW or NHWC, got "
                         f"{data_format!r}")
    r = upscale_factor
    if data_format == "NCHW":
        n, c, h, w = x.shape
        oc = c // (r * r)
        x = x.reshape(n, oc, r, r, h, w)
        x = jnp.transpose(x, (0, 1, 4, 2, 5, 3))
        return x.reshape(n, oc, h * r, w * r)
    n, h, w, c = x.shape
    oc = c // (r * r)
    # input channels interpreted (oc, rh, rw), matching the reference's
    # NHWC reshape + axis {0,1,4,2,5,3} (pixel_shuffle_kernel_impl.h:42)
    x = x.reshape(n, h, w, oc, r, r)
    x = jnp.transpose(x, (0, 1, 4, 2, 5, 3))
    return x.reshape(n, h * r, w * r, oc)


@defop("pixel_unshuffle")
def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    if data_format not in ("NCHW", "NHWC"):
        raise ValueError(f"data_format must be NCHW or NHWC, got "
                         f"{data_format!r}")
    r = downscale_factor
    if data_format == "NCHW":
        n, c, h, w = x.shape
        x = x.reshape(n, c, h // r, r, w // r, r)
        x = jnp.transpose(x, (0, 1, 3, 5, 2, 4))
        return x.reshape(n, c * r * r, h // r, w // r)
    n, h, w, c = x.shape                       # NHWC
    x = x.reshape(n, h // r, r, w // r, r, c)
    # out channels ordered (c, rh, rw), matching the reference's NHWC
    # transpose axis {0,1,3,5,2,4} (pixel_unshuffle_kernel_impl.h:43)
    x = jnp.transpose(x, (0, 1, 3, 5, 2, 4))
    return x.reshape(n, h // r, w // r, c * r * r)


@defop("channel_shuffle")
def channel_shuffle(x, groups, data_format="NCHW", name=None):
    if data_format not in ("NCHW", "NHWC"):
        raise ValueError(f"data_format must be NCHW or NHWC, got "
                         f"{data_format!r}")
    if data_format == "NCHW":
        n, c, h, w = x.shape
        x = x.reshape(n, groups, c // groups, h, w)
        x = jnp.swapaxes(x, 1, 2)
        return x.reshape(n, c, h, w)
    n, h, w, c = x.shape                       # NHWC
    x = x.reshape(n, h, w, groups, c // groups)
    x = jnp.swapaxes(x, 3, 4)
    return x.reshape(n, h, w, c)


# ---------------------------------------------------------------------------
# unfold / fold (im2col)
# ---------------------------------------------------------------------------
@defop("unfold_op")
def _unfold(x, kernel_sizes, strides, paddings, dilations):
    n, c, h, w = x.shape
    kh, kw = kernel_sizes
    sh, sw = strides
    ph, pw = paddings[0], paddings[1]
    dh, dw = dilations
    x = jnp.pad(x, [(0, 0), (0, 0), (ph, ph), (pw, pw)])
    oh = (h + 2 * ph - dh * (kh - 1) - 1) // sh + 1
    ow = (w + 2 * pw - dw * (kw - 1) - 1) // sw + 1
    patches = []
    for i in range(kh):
        for j in range(kw):
            sl = x[:, :, i * dh:i * dh + oh * sh:sh, j * dw:j * dw + ow * sw:sw]
            patches.append(sl)
    out = jnp.stack(patches, axis=2)  # n, c, kh*kw, oh, ow
    return out.reshape(n, c * kh * kw, oh * ow)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    def _pair(v):
        return tuple(v) if isinstance(v, (list, tuple)) else (v, v)
    return _unfold(x, kernel_sizes=_pair(kernel_sizes),
                   strides=_pair(strides), paddings=_pair(paddings),
                   dilations=_pair(dilations))


@defop("fold_op")
def _fold(x, output_sizes, kernel_sizes, strides, paddings, dilations):
    n, ckk, l = x.shape
    kh, kw = kernel_sizes
    c = ckk // (kh * kw)
    oh_t, ow_t = output_sizes
    sh, sw = strides
    ph, pw = paddings
    dh, dw = dilations
    oh = (oh_t + 2 * ph - dh * (kh - 1) - 1) // sh + 1
    ow = (ow_t + 2 * pw - dw * (kw - 1) - 1) // sw + 1
    xr = x.reshape(n, c, kh, kw, oh, ow)
    out = jnp.zeros((n, c, oh_t + 2 * ph, ow_t + 2 * pw), x.dtype)
    for i in range(kh):
        for j in range(kw):
            out = out.at[:, :, i * dh:i * dh + oh * sh:sh,
                         j * dw:j * dw + ow * sw:sw].add(xr[:, :, i, j])
    return out[:, :, ph:ph + oh_t, pw:pw + ow_t]


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1,
         name=None):
    def _pair(v):
        return tuple(v) if isinstance(v, (list, tuple)) else (v, v)
    return _fold(x, output_sizes=_pair(output_sizes),
                 kernel_sizes=_pair(kernel_sizes), strides=_pair(strides),
                 paddings=_pair(paddings), dilations=_pair(dilations))


# ---------------------------------------------------------------------------
# misc
# ---------------------------------------------------------------------------
@defop("label_smooth_op")
def _label_smooth(label, epsilon=0.1, prior_dist=None):
    k = label.shape[-1]
    if prior_dist is not None:
        return (1 - epsilon) * label + epsilon * prior_dist
    return (1 - epsilon) * label + epsilon / k


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    return _label_smooth(label, epsilon=epsilon, prior_dist=prior_dist)


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    lengths = x      # reference param name is x (nn/functional/common.py)
    lv = lengths._value if isinstance(lengths, Tensor) else jnp.asarray(lengths)
    m = int(maxlen) if maxlen is not None else int(jnp.max(lv))
    mask = jnp.arange(m)[None, :] < lv[..., None]
    return Tensor(mask.astype(dtypes.convert_dtype(dtype)))


@defop("temporal_shift")
def temporal_shift(x, seg_num, shift_ratio=0.25, name=None,
                   data_format="NCHW"):
    nt, c, h, w = x.shape
    n = nt // seg_num
    xr = x.reshape(n, seg_num, c, h, w)
    fold_c = int(c * shift_ratio)
    left = jnp.concatenate([xr[:, 1:, :fold_c],
                            jnp.zeros_like(xr[:, :1, :fold_c])], axis=1)
    right = jnp.concatenate([jnp.zeros_like(xr[:, :1, fold_c:2 * fold_c]),
                             xr[:, :-1, fold_c:2 * fold_c]], axis=1)
    rest = xr[:, :, 2 * fold_c:]
    return jnp.concatenate([left, right, rest], axis=2).reshape(nt, c, h, w)


def diag_embed(input, offset=0, dim1=-2, dim2=-1):
    x = input
    xv = x._value if isinstance(x, Tensor) else jnp.asarray(x)
    n = xv.shape[-1]
    base = jnp.zeros(xv.shape[:-1] + (n + abs(offset), n + abs(offset)), xv.dtype)
    idx = jnp.arange(n)
    if offset >= 0:
        out = base.at[..., idx, idx + offset].set(xv)
    else:
        out = base.at[..., idx - offset, idx].set(xv)
    if (dim1, dim2) != (-2, -1):
        out = jnp.moveaxis(out, (-2, -1), (dim1, dim2))
    return Tensor(out)
