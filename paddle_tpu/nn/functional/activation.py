"""Activation functions (reference: python/paddle/nn/functional/activation.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core.dispatch import defop


@defop("relu")
def relu(x, name=None):
    return jax.nn.relu(x)


@defop("relu6")
def relu6(x, name=None):
    return jax.nn.relu6(x)


@defop("relu_")
def _relu_inplace(x):
    return jax.nn.relu(x)


def relu_(x, name=None):
    return x._inplace_assign(_relu_inplace(x))


@defop("gelu")
def gelu(x, approximate=False, name=None):
    return jax.nn.gelu(x, approximate=bool(approximate))


@defop("silu")
def silu(x, name=None):
    return jax.nn.silu(x)


swish = silu


@defop("sigmoid_act")
def sigmoid(x, name=None):
    return jax.nn.sigmoid(x)


@defop("hardsigmoid")
def hardsigmoid(x, slope=0.1666667, offset=0.5, name=None):
    return jnp.clip(slope * x + offset, 0.0, 1.0)


@defop("hardswish")
def hardswish(x, name=None):
    return x * jnp.clip(x / 6.0 + 0.5, 0.0, 1.0)


@defop("hardtanh")
def hardtanh(x, min=-1.0, max=1.0, name=None):
    return jnp.clip(x, min, max)


@defop("hardshrink")
def hardshrink(x, threshold=0.5, name=None):
    return jnp.where(jnp.abs(x) > threshold, x, 0.0)


@defop("softshrink")
def softshrink(x, threshold=0.5, name=None):
    return jnp.where(x > threshold, x - threshold,
                     jnp.where(x < -threshold, x + threshold, 0.0))


@defop("tanhshrink")
def tanhshrink(x, name=None):
    return x - jnp.tanh(x)


@defop("leaky_relu")
def leaky_relu(x, negative_slope=0.01, name=None):
    return jax.nn.leaky_relu(x, negative_slope)


@defop("elu")
def elu(x, alpha=1.0, name=None):
    return jax.nn.elu(x, alpha)


def elu_(x, alpha=1.0, name=None):
    return x._inplace_assign(elu(x, alpha))


@defop("selu")
def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return scale * jnp.where(x > 0, x, alpha * jnp.expm1(x))


@defop("celu")
def celu(x, alpha=1.0, name=None):
    return jax.nn.celu(x, alpha)


@defop("prelu_op")
def _prelu(x, weight, data_format="NCHW"):
    if weight.size == 1:
        w = weight.reshape(())
    else:
        shape = [1] * x.ndim
        ch_axis = 1 if data_format[1] == "C" else x.ndim - 1
        shape[ch_axis] = weight.size
        w = weight.reshape(shape)
    return jnp.where(x > 0, x, w * x)


def prelu(x, weight, data_format="NCHW", name=None):
    return _prelu(x, weight, data_format=data_format)


@defop("rrelu", differentiable=True)
def rrelu(x, lower=0.125, upper=0.3333333, training=True, name=None):
    slope = (lower + upper) / 2.0
    return jnp.where(x >= 0, x, slope * x)


@defop("softmax", amp_policy="black")
def _softmax(x, axis=-1):
    return jax.nn.softmax(x, axis=axis)


def softmax(x, axis=-1, dtype=None, name=None):
    if dtype is not None:
        from paddle_tpu.tensor.manipulation import cast
        x = cast(x, dtype)
    return _softmax(x, axis=axis)


def softmax_(x, axis=-1, dtype=None, name=None):
    return x._inplace_assign(softmax(x, axis, dtype))


@defop("log_softmax", amp_policy="black")
def _log_softmax(x, axis=-1):
    return jax.nn.log_softmax(x, axis=axis)


def log_softmax(x, axis=-1, dtype=None, name=None):
    if dtype is not None:
        from paddle_tpu.tensor.manipulation import cast
        x = cast(x, dtype)
    return _log_softmax(x, axis=axis)


@defop("softplus")
def softplus(x, beta=1, threshold=20, name=None):
    return jnp.where(x * beta > threshold, x,
                     jax.nn.softplus(x * beta) / beta)


@defop("softsign")
def softsign(x, name=None):
    return jax.nn.soft_sign(x)


@defop("mish")
def mish(x, name=None):
    return x * jnp.tanh(jax.nn.softplus(x))


@defop("maxout")
def maxout(x, groups, axis=1, name=None):
    c = x.shape[axis]
    new_shape = list(x.shape)
    new_shape[axis] = c // groups
    new_shape.insert(axis + 1, groups)
    return jnp.max(x.reshape(new_shape), axis=axis + 1)


@defop("glu")
def glu(x, axis=-1, name=None):
    a, b = jnp.split(x, 2, axis=axis)
    return a * jax.nn.sigmoid(b)


@defop("tanh_act")
def tanh(x):
    return jnp.tanh(x)


@defop("thresholded_relu")
def thresholded_relu(x, threshold=1.0, value=0.0, name=None):
    return jnp.where(x > threshold, x, value)


@defop("log_sigmoid", amp_policy="black")
def log_sigmoid(x, name=None):
    return jax.nn.log_sigmoid(x)


@defop("gumbel_softmax_impl")
def _gumbel_softmax(x, key, temperature=1.0, hard=False, axis=-1):
    g = jax.random.gumbel(key, x.shape,
                          x.dtype if x.dtype in (jnp.float32, jnp.bfloat16,
                                                 jnp.float16) else jnp.float32)
    y = jax.nn.softmax((x + g.astype(x.dtype)) / temperature, axis=axis)
    if hard:
        idx = jnp.argmax(y, axis=axis, keepdims=True)
        y_hard = jnp.zeros_like(y)
        # the axis dim's coordinate IS idx; building an arange broadcast
        # for it too would try to broadcast (1, C) onto idx's (..., 1)
        dims = [idx if d == axis % y.ndim else jnp.broadcast_to(
            jnp.arange(y.shape[d]).reshape(
                [-1 if i == d else 1 for i in range(y.ndim)]), idx.shape)
            for d in range(y.ndim)]
        y_hard = y_hard.at[tuple(dims)].set(1.0)
        # straight-through estimator
        y = jax.lax.stop_gradient(y_hard - y) + y
    return y


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    from paddle_tpu.core.random import next_key
    return _gumbel_softmax(x, next_key(), temperature=temperature, hard=hard,
                           axis=axis)
