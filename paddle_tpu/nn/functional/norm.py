"""Normalization (reference: python/paddle/nn/functional/norm.py).

Stat math is done in float32 regardless of input dtype (bf16-safe), matching
the reference's fp32 accumulation in its CUDA kernels
(phi/kernels/gpu/layer_norm_kernel.cu).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core.dispatch import defop
from paddle_tpu.core.tensor import Tensor


@defop("layer_norm", amp_policy="black")
def _layer_norm(x, weight=None, bias=None, normalized_ndim=1, epsilon=1e-5):
    axes = tuple(range(x.ndim - normalized_ndim, x.ndim))
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=axes, keepdims=True)
    var = jnp.var(xf, axis=axes, keepdims=True)
    out = (xf - mean) * jax.lax.rsqrt(var + epsilon)
    if weight is not None:
        out = out * weight.astype(jnp.float32)
    if bias is not None:
        out = out + bias.astype(jnp.float32)
    return out.astype(x.dtype)


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5,
               name=None):
    ns = normalized_shape if isinstance(normalized_shape, (list, tuple)) \
        else [normalized_shape]
    return _layer_norm(x, weight, bias, normalized_ndim=len(ns),
                       epsilon=epsilon)


@defop("rms_norm_ref", amp_policy="black",
       spmd_note="replicated scale; seq/batch dims freely shardable")
def _rms_norm(x, weight=None, epsilon=1e-6):
    xf = x.astype(jnp.float32)
    ms = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    out = xf * jax.lax.rsqrt(ms + epsilon)
    if weight is not None:
        out = out * weight.astype(jnp.float32)
    return out.astype(x.dtype)


def rms_norm(x, weight=None, epsilon=1e-6, name=None):
    """RMSNorm (reference: python/paddle/incubate/nn/functional/fused_rms_norm.py
    — there a fused CUDA kernel; here XLA fuses the jnp chain, with a Pallas
    fused kernel in paddle_tpu.kernels for long rows)."""
    return _rms_norm(x, weight, epsilon=epsilon)


@defop("rms_norm_residual", amp_policy="black",
       spmd_note="replicated scale; batch/seq dims freely shardable "
                 "(same contract as rms_norm_ref)")
def _rms_norm_residual_op(x, residual=None, weight=None, epsilon=1e-6,
                          kernel=None):
    """Fused `h = x + residual; y = rms_norm(h) * weight` — one read of
    x, the residual sum written in the same pass, closed-form fused
    backward (kernels/fused_norm.py). Returns (y, h); with
    residual=None h is x and this is the plain norm as ONE vjp op
    (exact rms_norm_ref numerics either way)."""
    from paddle_tpu.kernels.fused_norm import rms_norm_residual
    return rms_norm_residual(x, weight, residual=residual,
                             epsilon=epsilon, kernel=kernel)


def rms_norm_fused(x, weight, epsilon=1e-6, residual=None, kernel=None,
                   name=None):
    """Tensor surface of the fused RMSNorm(+residual) train-path op
    (ISSUE 14's `kernels/fused_norm.py`; reference kernel
    fused_layernorm_kernel.cu rmsnorm branch). Returns (normed, h)
    where h = x + residual (or x itself when residual is None)."""
    return _rms_norm_residual_op(x, residual, weight, epsilon=epsilon,
                                 kernel=kernel)


@defop("batch_norm_infer", amp_policy="black")
def _batch_norm_infer(x, running_mean, running_var, weight, bias,
                      epsilon=1e-5, channel_axis=1):
    shape = [1] * x.ndim
    shape[channel_axis] = x.shape[channel_axis]
    xf = x.astype(jnp.float32)
    out = (xf - running_mean.reshape(shape)) * \
        jax.lax.rsqrt(running_var.reshape(shape).astype(jnp.float32) + epsilon)
    if weight is not None:
        out = out * weight.reshape(shape)
    if bias is not None:
        out = out + bias.reshape(shape)
    return out.astype(x.dtype)


@defop("batch_norm_train", amp_policy="black")
def _batch_norm_train(x, weight, bias, epsilon=1e-5, channel_axis=1):
    axes = tuple(i for i in range(x.ndim) if i != channel_axis)
    shape = [1] * x.ndim
    shape[channel_axis] = x.shape[channel_axis]
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=axes)
    var = jnp.var(xf, axis=axes)
    out = (xf - mean.reshape(shape)) * \
        jax.lax.rsqrt(var.reshape(shape) + epsilon)
    if weight is not None:
        out = out * weight.reshape(shape)
    if bias is not None:
        out = out + bias.reshape(shape)
    return out.astype(x.dtype), mean, var


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-5,
               data_format="NCHW", use_global_stats=None, name=None):
    ch = 1 if data_format.startswith("NC") else x.ndim - 1
    if use_global_stats is None:
        use_global_stats = not training
    if use_global_stats:
        return _batch_norm_infer(x, running_mean, running_var, weight, bias,
                                 epsilon=epsilon, channel_axis=ch)
    out, mean, var = _batch_norm_train(x, weight, bias, epsilon=epsilon,
                                       channel_axis=ch)
    # eager running-stat update (buffers are mutable handles)
    if isinstance(running_mean, Tensor) and not isinstance(
            mean._value, jax.core.Tracer):
        running_mean._value = (momentum * running_mean._value +
                               (1 - momentum) * mean._value).astype(
            running_mean._value.dtype)
        running_var._value = (momentum * running_var._value +
                              (1 - momentum) * var._value).astype(
            running_var._value.dtype)
    return out


@defop("group_norm_op", amp_policy="black")
def _group_norm(x, weight=None, bias=None, num_groups=1, epsilon=1e-5,
                channel_axis=1):
    c = x.shape[channel_axis]
    if channel_axis != 1:
        x_m = jnp.moveaxis(x, channel_axis, 1)
    else:
        x_m = x
    n = x_m.shape[0]
    xf = x_m.astype(jnp.float32).reshape(n, num_groups, c // num_groups, -1)
    mean = jnp.mean(xf, axis=(2, 3), keepdims=True)
    var = jnp.var(xf, axis=(2, 3), keepdims=True)
    out = ((xf - mean) * jax.lax.rsqrt(var + epsilon)).reshape(x_m.shape)
    shape = [1, c] + [1] * (x_m.ndim - 2)
    if weight is not None:
        out = out * weight.reshape(shape)
    if bias is not None:
        out = out + bias.reshape(shape)
    out = out.astype(x.dtype)
    if channel_axis != 1:
        out = jnp.moveaxis(out, 1, channel_axis)
    return out


def group_norm(x, num_groups, epsilon=1e-5, weight=None, bias=None,
               data_format="NCHW", name=None):
    ch = 1 if data_format.startswith("NC") else x.ndim - 1
    return _group_norm(x, weight, bias, num_groups=num_groups,
                       epsilon=epsilon, channel_axis=ch)


@defop("instance_norm_op", amp_policy="black")
def _instance_norm(x, weight=None, bias=None, epsilon=1e-5):
    axes = tuple(range(2, x.ndim))
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=axes, keepdims=True)
    var = jnp.var(xf, axis=axes, keepdims=True)
    out = (xf - mean) * jax.lax.rsqrt(var + epsilon)
    shape = [1, x.shape[1]] + [1] * (x.ndim - 2)
    if weight is not None:
        out = out * weight.reshape(shape)
    if bias is not None:
        out = out + bias.reshape(shape)
    return out.astype(x.dtype)


def instance_norm(x, running_mean=None, running_var=None, weight=None,
                  bias=None, use_input_stats=True, momentum=0.9, eps=1e-5,
                  data_format="NCHW", name=None):
    return _instance_norm(x, weight, bias, epsilon=eps)


@defop("local_response_norm_op", amp_policy="black")
def _local_response_norm(x, size=5, alpha=1e-4, beta=0.75, k=1.0):
    sq = jnp.square(x.astype(jnp.float32))
    c = x.shape[1]
    half = size // 2
    padded = jnp.pad(sq, [(0, 0), (half, size - 1 - half)] +
                     [(0, 0)] * (x.ndim - 2))
    window = sum(padded[:, i:i + c] for i in range(size))
    return (x.astype(jnp.float32) /
            jnp.power(k + alpha * window, beta)).astype(x.dtype)


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW", name=None):
    return _local_response_norm(x, size=size, alpha=alpha, beta=beta, k=k)
