"""Convolutions (reference: python/paddle/nn/functional/conv.py).

All convs lower to jax.lax.conv_general_dilated — XLA tiles them onto the
MXU directly (the reference needs cuDNN algorithm search + autotune cache,
paddle/phi/kernels/autotune/; XLA picks layouts/tilings at compile time).
Paddle's NCHW/OIHW conventions are kept at the API boundary; XLA is free to
re-layout internally for TPU.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.core.dispatch import defop


def _tuple(v, n):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in v)
    return (int(v),) * n


def _padding(padding, n, stride, kernel, dilation):
    """paddle padding: int, list, pairs, or 'SAME'/'VALID'."""
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, (list, tuple)):
        flat = list(padding)
        if len(flat) == n:
            return [(int(p), int(p)) for p in flat]
        if len(flat) == 2 * n:
            return [(int(flat[2 * i]), int(flat[2 * i + 1])) for i in range(n)]
        if all(isinstance(p, (list, tuple)) for p in flat):
            # NCHW-style per-dim pairs incl batch/channel: take spatial
            sp = flat[-n:]
            return [(int(a), int(b)) for a, b in sp]
    return [(int(padding), int(padding))] * n


def _conv_nd(x, weight, bias, stride, padding, dilation, groups, n,
             channel_last=False, preferred_element_type=None):
    # build dimension spec strings like NCHW / OIHW
    sp = "DHW"[-n:] if n == 3 else ("HW" if n == 2 else "W")
    lhs = ("N" + sp + "C") if channel_last else ("NC" + sp)
    rhs = "OI" + sp
    out = lhs
    dn = jax.lax.conv_dimension_numbers(x.shape, weight.shape, (lhs, rhs, out))
    y = jax.lax.conv_general_dilated(
        x, weight, window_strides=stride, padding=padding,
        lhs_dilation=(1,) * n, rhs_dilation=dilation,
        dimension_numbers=dn, feature_group_count=groups,
        preferred_element_type=preferred_element_type)
    if bias is not None:
        if channel_last:
            y = y + bias.reshape((1,) * (y.ndim - 1) + (-1,))
        else:
            y = y + bias.reshape((1, -1) + (1,) * n)
    return y


@defop("conv1d", amp_policy="white")
def _conv1d(x, weight, bias=None, stride=(1,), padding=((0, 0),),
            dilation=(1,), groups=1, channel_last=False):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, 1,
                    channel_last)


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    return _conv1d(x, weight, bias, stride=_tuple(stride, 1),
                   padding=_padding(padding, 1, stride, None, dilation),
                   dilation=_tuple(dilation, 1), groups=groups,
                   channel_last=(data_format == "NLC"))


@defop("conv2d", amp_policy="white",
       spmd_note="batch->dp, out-channels->mp shardable")
def _conv2d(x, weight, bias=None, stride=(1, 1), padding=((0, 0), (0, 0)),
            dilation=(1, 1), groups=1, channel_last=False):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, 2,
                    channel_last)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    return _conv2d(x, weight, bias, stride=_tuple(stride, 2),
                   padding=_padding(padding, 2, stride, None, dilation),
                   dilation=_tuple(dilation, 2), groups=groups,
                   channel_last=(data_format == "NHWC"))


@defop("conv3d", amp_policy="white")
def _conv3d(x, weight, bias=None, stride=(1, 1, 1),
            padding=((0, 0), (0, 0), (0, 0)), dilation=(1, 1, 1), groups=1,
            channel_last=False):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, 3,
                    channel_last)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    return _conv3d(x, weight, bias, stride=_tuple(stride, 3),
                   padding=_padding(padding, 3, stride, None, dilation),
                   dilation=_tuple(dilation, 3), groups=groups,
                   channel_last=(data_format == "NDHWC"))


def _conv_transpose_nd(x, weight, bias, stride, padding, output_padding,
                       dilation, groups, n):
    # weight layout (paddle): (in_channels, out_channels/groups, *k)
    sp = "HW" if n == 2 else ("W" if n == 1 else "DHW")
    lhs = "NC" + sp
    rhs = "IO" + sp
    dn = jax.lax.conv_dimension_numbers(
        x.shape, weight.shape, (lhs, rhs, lhs))
    if isinstance(padding, str):
        pad = padding
    else:
        # transpose conv padding: effective padding = dilation*(k-1) - pad
        k = weight.shape[2:]
        pad = [(dilation[i] * (k[i] - 1) - padding[i][0],
                dilation[i] * (k[i] - 1) - padding[i][1] + output_padding[i])
               for i in range(n)]
    def one_group(xi, wi):
        # wi: (in/g, out/g, *k) -> (out/g, in/g, *k), spatially flipped
        w = jnp.flip(jnp.swapaxes(wi, 0, 1), axis=tuple(range(2, 2 + n)))
        dn2 = jax.lax.conv_dimension_numbers(
            xi.shape, w.shape, ("NC" + sp, "OI" + sp, "NC" + sp))
        return jax.lax.conv_general_dilated(
            xi, w, window_strides=(1,) * n, padding=pad,
            lhs_dilation=stride, rhs_dilation=dilation,
            dimension_numbers=dn2)

    if groups > 1:
        xs = jnp.split(x, groups, axis=1)
        ws = jnp.split(weight, groups, axis=0)
        y = jnp.concatenate([one_group(xi, wi) for xi, wi in zip(xs, ws)],
                            axis=1)
    else:
        y = one_group(x, weight)
    if bias is not None:
        y = y + bias.reshape((1, -1) + (1,) * n)
    return y


@defop("conv2d_transpose", amp_policy="white")
def _conv2d_transpose(x, weight, bias=None, stride=(1, 1),
                      padding=((0, 0), (0, 0)), output_padding=(0, 0),
                      dilation=(1, 1), groups=1):
    return _conv_transpose_nd(x, weight, bias, stride, padding,
                              output_padding, dilation, groups, 2)


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1, output_size=None,
                     data_format="NCHW", name=None):
    return _conv2d_transpose(
        x, weight, bias, stride=_tuple(stride, 2),
        padding=_padding(padding, 2, stride, None, dilation),
        output_padding=_tuple(output_padding, 2),
        dilation=_tuple(dilation, 2), groups=groups)


@defop("conv1d_transpose", amp_policy="white")
def _conv1d_transpose(x, weight, bias=None, stride=(1,), padding=((0, 0),),
                      output_padding=(0,), dilation=(1,), groups=1):
    return _conv_transpose_nd(x, weight, bias, stride, padding,
                              output_padding, dilation, groups, 1)


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1, output_size=None,
                     data_format="NCL", name=None):
    return _conv1d_transpose(
        x, weight, bias, stride=_tuple(stride, 1),
        padding=_padding(padding, 1, stride, None, dilation),
        output_padding=_tuple(output_padding, 1),
        dilation=_tuple(dilation, 1), groups=groups)


@defop("conv3d_transpose", amp_policy="white")
def _conv3d_transpose(x, weight, bias=None, stride=(1, 1, 1),
                      padding=((0, 0),) * 3, output_padding=(0, 0, 0),
                      dilation=(1, 1, 1), groups=1):
    return _conv_transpose_nd(x, weight, bias, stride, padding,
                              output_padding, dilation, groups, 3)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1, output_size=None,
                     data_format="NCDHW", name=None):
    return _conv3d_transpose(
        x, weight, bias, stride=_tuple(stride, 3),
        padding=_padding(padding, 3, stride, None, dilation),
        output_padding=_tuple(output_padding, 3),
        dilation=_tuple(dilation, 3), groups=groups)
