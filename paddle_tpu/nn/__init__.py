"""paddle_tpu.nn (reference: python/paddle/nn/__init__.py)."""
from paddle_tpu.nn.layer.layers import Layer, ParamAttr  # noqa: F401
from paddle_tpu.nn.layer.common import *  # noqa: F401,F403
from paddle_tpu.nn.layer.conv_pool import *  # noqa: F401,F403
from paddle_tpu.nn.layer.norm import *  # noqa: F401,F403
from paddle_tpu.nn.layer.activation import *  # noqa: F401,F403
from paddle_tpu.nn.layer.loss import *  # noqa: F401,F403
from paddle_tpu.nn.layer.container import (  # noqa: F401
    Sequential, LayerList, LayerDict, ParameterList)
from paddle_tpu.nn.layer.transformer import (  # noqa: F401
    MultiHeadAttention, Transformer, TransformerEncoder,
    TransformerEncoderLayer, TransformerDecoder, TransformerDecoderLayer)
from paddle_tpu.nn.layer.rnn import (  # noqa: F401
    SimpleRNNCell, LSTMCell, GRUCell, SimpleRNN, LSTM, GRU, RNN, BiRNN,
    RNNCellBase)
from paddle_tpu.nn import functional  # noqa: F401
from paddle_tpu.nn import initializer  # noqa: F401
from paddle_tpu.core.tensor import Parameter  # noqa: F401


class ClipGradByNorm:
    """Reference: python/paddle/nn/clip.py ClipGradByNorm."""

    def __init__(self, clip_norm):
        self.clip_norm = clip_norm


class ClipGradByValue:
    def __init__(self, max, min=None):
        self.max = max
        self.min = min if min is not None else -max


class ClipGradByGlobalNorm:
    """Reference: python/paddle/nn/clip.py ClipGradByGlobalNorm:
    scale all grads by clip_norm/global_norm when exceeded. The actual
    clipping happens inside Optimizer.step (like the reference's
    _dygraph_clip), and inside the fused jit train step for the compiled
    path. Under hybrid parallel, the global norm is computed across all
    shards (GSPMD reduces automatically for sharded grads)."""

    def __init__(self, clip_norm, group_name="default_group",
                 auto_skip_clip=False):
        self.clip_norm = clip_norm

from paddle_tpu.nn.layer.extras import *  # noqa: F401,F403,E402
