"""Weight initializers (reference: python/paddle/nn/initializer/).

Initializers are pure functions (shape, dtype) -> jax.Array drawing from the
global Generator — no in-place "init ops" like the reference (its
initializers append fill ops to a startup program / mutate eager tensors).
"""
from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.core import dtype as dtypes
from paddle_tpu.core.random import next_key


def _fans(shape):
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        # paddle Linear weight layout is (in_features, out_features)
        return shape[0], shape[1]
    receptive = int(np.prod(shape[2:]))
    fan_in = shape[1] * receptive
    fan_out = shape[0] * receptive
    return fan_in, fan_out


def calculate_gain(nonlinearity, param=None):
    recommended = {
        "sigmoid": 1.0, "linear": 1.0, "conv1d": 1.0, "conv2d": 1.0,
        "conv3d": 1.0, "tanh": 5.0 / 3.0, "relu": math.sqrt(2.0),
        "leaky_relu": math.sqrt(2.0 / (1 + (param if param is not None else 0.01) ** 2)),
        "selu": 3.0 / 4.0,
    }
    return recommended[nonlinearity]


class Initializer:
    def __call__(self, shape, dtype):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype):
        return jnp.full(shape, self.value, dtypes.convert_dtype(dtype))


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0, name=None):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype):
        dt = dtypes.convert_dtype(dtype)
        return (jax.random.normal(next_key(), shape, jnp.float32) * self.std
                + self.mean).astype(dt)


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0, a=-2.0, b=2.0, name=None):
        self.mean, self.std, self.a, self.b = mean, std, a, b

    def __call__(self, shape, dtype):
        dt = dtypes.convert_dtype(dtype)
        r = jax.random.truncated_normal(next_key(), self.a, self.b, shape,
                                        jnp.float32)
        return (r * self.std + self.mean).astype(dt)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0, name=None):
        self.low, self.high = low, high

    def __call__(self, shape, dtype):
        dt = dtypes.convert_dtype(dtype)
        return jax.random.uniform(next_key(), shape, jnp.float32,
                                  self.low, self.high).astype(dt)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0, name=None):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        dt = dtypes.convert_dtype(dtype)
        return (jax.random.normal(next_key(), shape, jnp.float32) * std).astype(dt)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0, name=None):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        dt = dtypes.convert_dtype(dtype)
        return jax.random.uniform(next_key(), shape, jnp.float32,
                                  -limit, limit).astype(dt)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu",
                 name=None):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in or fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        std = gain / math.sqrt(fi)
        dt = dtypes.convert_dtype(dtype)
        return (jax.random.normal(next_key(), shape, jnp.float32) * std).astype(dt)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu",
                 name=None):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in or fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        limit = gain * math.sqrt(3.0 / fi)
        dt = dtypes.convert_dtype(dtype)
        return jax.random.uniform(next_key(), shape, jnp.float32,
                                  -limit, limit).astype(dt)


class Assign(Initializer):
    def __init__(self, value, name=None):
        self.value = value

    def __call__(self, shape, dtype):
        from paddle_tpu.core.tensor import Tensor
        v = self.value
        arr = v._value if isinstance(v, Tensor) else jnp.asarray(np.asarray(v))
        if tuple(arr.shape) != tuple(shape):
            arr = arr.reshape(shape)
        return arr.astype(dtypes.convert_dtype(dtype))


class Orthogonal(Initializer):
    def __init__(self, gain=1.0, name=None):
        self.gain = gain

    def __call__(self, shape, dtype):
        dt = dtypes.convert_dtype(dtype)
        rows, cols = shape[0], int(np.prod(shape[1:]))
        n = max(rows, cols)
        a = jax.random.normal(next_key(), (n, n), jnp.float32)
        q, r = jnp.linalg.qr(a)
        q = q * jnp.sign(jnp.diag(r))
        return (self.gain * q[:rows, :cols].reshape(shape)).astype(dt)


class Dirac(Initializer):
    def __init__(self, groups=1, name=None):
        self.groups = groups

    def __call__(self, shape, dtype):
        out = np.zeros(shape, dtype=np.float32)
        oc, ic = shape[0], shape[1]
        mins = min(oc // self.groups, ic)
        centers = [s // 2 for s in shape[2:]]
        for g in range(self.groups):
            for i in range(mins):
                idx = (g * (oc // self.groups) + i, i) + tuple(centers)
                out[idx] = 1.0
        return jnp.asarray(out).astype(dtypes.convert_dtype(dtype))


# lowercase aliases used by the functional API
def set_global_initializer(weight_init, bias_init=None):
    global _global_weight_init, _global_bias_init
    _global_weight_init = weight_init
    _global_bias_init = bias_init


_global_weight_init = None
_global_bias_init = None
