"""Recurrent layers (reference: python/paddle/nn/layer/rnn.py).

Cells carry the math; the time loop is jax.lax.scan — XLA compiles one
fused step and loops it on-device (the reference dispatches per-timestep
kernels from a Python/C++ loop, or uses cuDNN's fused RNN; scan is the TPU
idiom for both).
"""
from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.core.dispatch import defop
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.nn import functional as F
from paddle_tpu.nn import initializer as I
from paddle_tpu.nn.layer.container import LayerList
from paddle_tpu.nn.layer.layers import Layer


class RNNCellBase(Layer):
    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0, batch_dim_idx=0):
        b = batch_ref.shape[batch_dim_idx]
        return Tensor(jnp.full((b, self.hidden_size), init_value,
                               jnp.float32))


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.activation = activation
        k = 1.0 / math.sqrt(hidden_size)
        u = I.Uniform(-k, k)
        self.weight_ih = self.create_parameter(
            [hidden_size, input_size], weight_ih_attr, default_initializer=u)
        self.weight_hh = self.create_parameter(
            [hidden_size, hidden_size], weight_hh_attr, default_initializer=u)
        self.bias_ih = self.create_parameter(
            [hidden_size], bias_ih_attr, is_bias=True, default_initializer=u)
        self.bias_hh = self.create_parameter(
            [hidden_size], bias_hh_attr, is_bias=True, default_initializer=u)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        h = _rnn_cell_step(inputs, states, self.weight_ih, self.weight_hh,
                           self.bias_ih, self.bias_hh,
                           activation=self.activation)
        return h, h

    @property
    def state_shape(self):
        return (self.hidden_size,)


@defop("simple_rnn_cell")
def _rnn_cell_step(x, h, w_ih, w_hh, b_ih, b_hh, activation="tanh"):
    z = x @ w_ih.T + b_ih + h @ w_hh.T + b_hh
    return jnp.tanh(z) if activation == "tanh" else jax.nn.relu(z)


class LSTMCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 proj_size=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        k = 1.0 / math.sqrt(hidden_size)
        u = I.Uniform(-k, k)
        self.weight_ih = self.create_parameter(
            [4 * hidden_size, input_size], weight_ih_attr,
            default_initializer=u)
        self.weight_hh = self.create_parameter(
            [4 * hidden_size, hidden_size], weight_hh_attr,
            default_initializer=u)
        self.bias_ih = self.create_parameter(
            [4 * hidden_size], bias_ih_attr, is_bias=True,
            default_initializer=u)
        self.bias_hh = self.create_parameter(
            [4 * hidden_size], bias_hh_attr, is_bias=True,
            default_initializer=u)

    def forward(self, inputs, states=None):
        if states is None:
            states = (self.get_initial_states(inputs),
                      self.get_initial_states(inputs))
        h, c = states
        h2, c2 = _lstm_cell_step(inputs, h, c, self.weight_ih,
                                 self.weight_hh, self.bias_ih, self.bias_hh)
        return h2, (h2, c2)

    @property
    def state_shape(self):
        return ((self.hidden_size,), (self.hidden_size,))


@defop("lstm_cell")
def _lstm_cell_step(x, h, c, w_ih, w_hh, b_ih, b_hh):
    gates = x @ w_ih.T + b_ih + h @ w_hh.T + b_hh
    i, f, g, o = jnp.split(gates, 4, axis=-1)
    i = jax.nn.sigmoid(i)
    f = jax.nn.sigmoid(f)
    g = jnp.tanh(g)
    o = jax.nn.sigmoid(o)
    c2 = f * c + i * g
    h2 = o * jnp.tanh(c2)
    return h2, c2


class GRUCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        k = 1.0 / math.sqrt(hidden_size)
        u = I.Uniform(-k, k)
        self.weight_ih = self.create_parameter(
            [3 * hidden_size, input_size], weight_ih_attr,
            default_initializer=u)
        self.weight_hh = self.create_parameter(
            [3 * hidden_size, hidden_size], weight_hh_attr,
            default_initializer=u)
        self.bias_ih = self.create_parameter(
            [3 * hidden_size], bias_ih_attr, is_bias=True,
            default_initializer=u)
        self.bias_hh = self.create_parameter(
            [3 * hidden_size], bias_hh_attr, is_bias=True,
            default_initializer=u)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        h = _gru_cell_step(inputs, states, self.weight_ih, self.weight_hh,
                           self.bias_ih, self.bias_hh)
        return h, h

    @property
    def state_shape(self):
        return (self.hidden_size,)


@defop("gru_cell")
def _gru_cell_step(x, h, w_ih, w_hh, b_ih, b_hh):
    gi = x @ w_ih.T + b_ih
    gh = h @ w_hh.T + b_hh
    ir, iz, in_ = jnp.split(gi, 3, axis=-1)
    hr, hz, hn = jnp.split(gh, 3, axis=-1)
    r = jax.nn.sigmoid(ir + hr)
    z = jax.nn.sigmoid(iz + hz)
    n = jnp.tanh(in_ + r * hn)
    return (1 - z) * n + z * h


@defop("rnn_scan")
def _rnn_scan(x_tbf, init_states, params, mode="LSTM"):
    """One direction over time with lax.scan. x: (T, B, F)."""
    if mode == "LSTM":
        w_ih, w_hh, b_ih, b_hh = params

        def step(carry, xt):
            h, c = carry
            h2, c2 = _lstm_cell_step.raw_fn(xt, h, c, w_ih, w_hh, b_ih, b_hh)
            return (h2, c2), h2

        carry, ys = jax.lax.scan(step, init_states, x_tbf)
        return ys, carry
    if mode == "GRU":
        w_ih, w_hh, b_ih, b_hh = params

        def step(h, xt):
            h2 = _gru_cell_step.raw_fn(xt, h, w_ih, w_hh, b_ih, b_hh)
            return h2, h2

        carry, ys = jax.lax.scan(step, init_states, x_tbf)
        return ys, carry
    w_ih, w_hh, b_ih, b_hh, act = params

    def step(h, xt):
        h2 = _rnn_cell_step.raw_fn(xt, h, w_ih, w_hh, b_ih, b_hh,
                                   activation=act)
        return h2, h2

    carry, ys = jax.lax.scan(step, init_states, x_tbf)
    return ys, carry


class RNNBase(Layer):
    """Multi-layer (bi)directional RNN driver (reference:
    nn/layer/rnn.py:RNNBase)."""

    MODE = "LSTM"

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        self.activation = activation
        self.bidirect = 2 if direction in ("bidirect", "bidirectional") else 1
        gate_mult = {"LSTM": 4, "GRU": 3, "RNN": 1}[self.MODE]
        k = 1.0 / math.sqrt(hidden_size)
        u = I.Uniform(-k, k)
        self._all_weights = []
        for layer in range(num_layers):
            for d in range(self.bidirect):
                in_sz = input_size if layer == 0 \
                    else hidden_size * self.bidirect
                suffix = f"_reverse" if d else ""
                w_ih = self.create_parameter(
                    [gate_mult * hidden_size, in_sz], weight_ih_attr,
                    default_initializer=u)
                w_hh = self.create_parameter(
                    [gate_mult * hidden_size, hidden_size], weight_hh_attr,
                    default_initializer=u)
                b_ih = self.create_parameter(
                    [gate_mult * hidden_size], bias_ih_attr, is_bias=True,
                    default_initializer=u)
                b_hh = self.create_parameter(
                    [gate_mult * hidden_size], bias_hh_attr, is_bias=True,
                    default_initializer=u)
                self.add_parameter(f"weight_ih_l{layer}{suffix}", w_ih)
                self.add_parameter(f"weight_hh_l{layer}{suffix}", w_hh)
                self.add_parameter(f"bias_ih_l{layer}{suffix}", b_ih)
                self.add_parameter(f"bias_hh_l{layer}{suffix}", b_hh)
                self._all_weights.append((w_ih, w_hh, b_ih, b_hh))

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from paddle_tpu.tensor import manipulation as M
        x = inputs
        if not self.time_major:
            x = M.transpose(x, [1, 0, 2])  # -> (T, B, F)
        t, b = x.shape[0], x.shape[1]
        n_dir = self.num_layers * self.bidirect
        if initial_states is None:
            z = Tensor(jnp.zeros((n_dir, b, self.hidden_size)))
            initial_states = (z, z.clone()) if self.MODE == "LSTM" else z
        final_h = []
        final_c = []
        out = x
        for layer in range(self.num_layers):
            dir_outs = []
            for d in range(self.bidirect):
                idx = layer * self.bidirect + d
                w = self._all_weights[idx]
                params = w if self.MODE in ("LSTM", "GRU") else \
                    (*w, self.activation)
                seq = out if d == 0 else M.flip(out, [0])
                if self.MODE == "LSTM":
                    h0 = initial_states[0][idx]
                    c0 = initial_states[1][idx]
                    ys, (hT, cT) = _rnn_scan(seq, (h0, c0), params,
                                             mode=self.MODE)
                    final_c.append(cT)
                else:
                    h0 = initial_states[idx]
                    ys, hT = _rnn_scan(seq, h0, params, mode=self.MODE)
                final_h.append(hT)
                if d == 1:
                    ys = M.flip(ys, [0])
                dir_outs.append(ys)
            out = dir_outs[0] if self.bidirect == 1 else \
                M.concat(dir_outs, axis=-1)
            if self.dropout > 0 and layer < self.num_layers - 1:
                out = F.dropout(out, self.dropout, training=self.training)
        if not self.time_major:
            out = M.transpose(out, [1, 0, 2])
        h_stack = M.stack(final_h, axis=0)
        if self.MODE == "LSTM":
            c_stack = M.stack(final_c, axis=0)
            return out, (h_stack, c_stack)
        return out, h_stack


class SimpleRNN(RNNBase):
    MODE = "RNN"

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", **kwargs):
        super().__init__(input_size, hidden_size, num_layers, direction,
                         time_major, dropout, activation, **kwargs)


class LSTM(RNNBase):
    MODE = "LSTM"


class GRU(RNNBase):
    MODE = "GRU"


class RNN(Layer):
    """Wraps a cell into a scan over time (reference: nn/layer/rnn.py:RNN)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None,
                **kwargs):
        from paddle_tpu.tensor import manipulation as M
        x = inputs
        if not self.time_major:
            x = M.transpose(x, [1, 0, 2])
        if self.is_reverse:
            x = M.flip(x, [0])
        states = initial_states
        outs = []
        for tstep in range(x.shape[0]):
            y, states = self.cell(x[tstep], states)
            outs.append(y)
        out = M.stack(outs, axis=0)
        if self.is_reverse:
            out = M.flip(out, [0])
        if not self.time_major:
            out = M.transpose(out, [1, 0, 2])
        return out, states


class BiRNN(Layer):
    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, False, time_major)
        self.rnn_bw = RNN(cell_bw, True, time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from paddle_tpu.tensor import manipulation as M
        s_fw, s_bw = (initial_states if initial_states is not None
                      else (None, None))
        o_fw, f_fw = self.rnn_fw(inputs, s_fw)
        o_bw, f_bw = self.rnn_bw(inputs, s_bw)
        return M.concat([o_fw, o_bw], axis=-1), (f_fw, f_bw)
