"""Activation layers (reference: python/paddle/nn/layer/activation.py)."""
from __future__ import annotations

from paddle_tpu.nn import functional as F
from paddle_tpu.nn import initializer as I
from paddle_tpu.nn.layer.layers import Layer


def _simple(fn_name, **fixed):
    class _Act(Layer):
        def __init__(self, *args, **kwargs):
            super().__init__()
            self._kwargs = dict(fixed)
            sig_args = _SIGS.get(fn_name, [])
            for name, val in zip(sig_args, args):
                self._kwargs[name] = val
            for k, v in kwargs.items():
                if k != "name":
                    self._kwargs[k] = v

        def forward(self, x):
            return getattr(F, fn_name)(x, **self._kwargs)

    _Act.__name__ = fn_name
    return _Act


_SIGS = {
    "leaky_relu": ["negative_slope"],
    "elu": ["alpha"],
    "celu": ["alpha"],
    "hardtanh": ["min", "max"],
    "hardshrink": ["threshold"],
    "softshrink": ["threshold"],
    "softplus": ["beta", "threshold"],
    "softmax": ["axis"],
    "log_softmax": ["axis"],
    "gelu": ["approximate"],
    "maxout": ["groups", "axis"],
    "glu": ["axis"],
    "thresholded_relu": ["threshold", "value"],
    "rrelu": ["lower", "upper"],
}

ReLU = _simple("relu")
ReLU6 = _simple("relu6")
GELU = _simple("gelu")
SiLU = _simple("silu")
Silu = SiLU
Swish = _simple("swish")
Sigmoid = _simple("sigmoid")
LogSigmoid = _simple("log_sigmoid")
Tanh = _simple("tanh")
Tanhshrink = _simple("tanhshrink")
Hardsigmoid = _simple("hardsigmoid")
Hardswish = _simple("hardswish")
Hardtanh = _simple("hardtanh")
Hardshrink = _simple("hardshrink")
Softshrink = _simple("softshrink")
LeakyReLU = _simple("leaky_relu")
ELU = _simple("elu")
CELU = _simple("celu")
SELU = _simple("selu")
Softplus = _simple("softplus")
Softsign = _simple("softsign")
Mish = _simple("mish")
Softmax = _simple("softmax")
LogSoftmax = _simple("log_softmax")
Maxout = _simple("maxout")
GLU = _simple("glu")
ThresholdedReLU = _simple("thresholded_relu")
RReLU = _simple("rrelu")
Softmax2D = _simple("softmax", axis=-3)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        self._data_format = data_format
        self.weight = self.create_parameter(
            shape=[num_parameters], attr=weight_attr,
            default_initializer=I.Constant(init))

    def forward(self, x):
        return F.prelu(x, self.weight, data_format=self._data_format)
