"""Normalization layers (reference: python/paddle/nn/layer/norm.py)."""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.nn import functional as F
from paddle_tpu.nn import initializer as I
from paddle_tpu.nn.layer.layers import Layer


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        ns = normalized_shape if isinstance(normalized_shape, (list, tuple)) \
            else [normalized_shape]
        self._normalized_shape = list(ns)
        self._epsilon = epsilon
        self.weight = None if weight_attr is False else self.create_parameter(
            shape=ns, attr=weight_attr, default_initializer=I.Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            shape=ns, attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.layer_norm(x, self._normalized_shape, self.weight,
                            self.bias, self._epsilon)

    def extra_repr(self):
        return f"normalized_shape={self._normalized_shape}, " \
               f"epsilon={self._epsilon}"


class RMSNorm(Layer):
    """LLM-standard RMS norm — reference exposes it as the fused op
    paddle.incubate.nn.functional.fused_rms_norm."""

    def __init__(self, hidden_size, epsilon=1e-6, weight_attr=None,
                 name=None):
        super().__init__()
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            shape=[hidden_size], attr=weight_attr,
            default_initializer=I.Constant(1.0))

    def forward(self, x):
        return F.rms_norm(x, self.weight, self._epsilon)


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        self.weight = None if weight_attr is False else self.create_parameter(
            shape=[num_features], attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            shape=[num_features], attr=bias_attr, is_bias=True)
        self.register_buffer("_mean", Tensor(jnp.zeros(num_features)))
        self.register_buffer("_variance", Tensor(jnp.ones(num_features)))

    def forward(self, x):
        return F.batch_norm(
            x, self._mean, self._variance, self.weight, self.bias,
            training=self.training, momentum=self._momentum,
            epsilon=self._epsilon, data_format=self._data_format,
            use_global_stats=self._use_global_stats)

    def extra_repr(self):
        return f"num_features={self._num_features}, " \
               f"momentum={self._momentum}, epsilon={self._epsilon}"


class BatchNorm(_BatchNormBase):
    """Legacy dygraph BatchNorm (reference: nn/layer/norm.py BatchNorm —
    the old num_channels-first signature, unlike BatchNorm1D/2D/3D).
    act/in_place/moving_*_name/do_model_average_* are accepted for
    signature parity; only `act` changes behavior here (post-norm
    activation), the rest are static-graph bookkeeping knobs."""

    def __init__(self, num_channels, act=None, is_test=False,
                 momentum=0.9, epsilon=1e-05, param_attr=None,
                 bias_attr=None, dtype='float32', data_layout='NCHW',
                 in_place=False, moving_mean_name=None,
                 moving_variance_name=None,
                 do_model_average_for_mean_and_var=True,
                 use_global_stats=False, trainable_statistics=False):
        super().__init__(num_channels, momentum=momentum, epsilon=epsilon,
                         weight_attr=param_attr, bias_attr=bias_attr,
                         data_format=data_layout,
                         use_global_stats=use_global_stats or None)
        self._act = act
        if is_test:
            self.eval()

    def forward(self, x):
        out = super().forward(x)
        if self._act:
            out = getattr(F, self._act)(out)
        return out


class BatchNorm1D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCL",
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, "NCHW" if data_format == "NCL" else
                         data_format, use_global_stats)


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCDHW",
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, data_format, use_global_stats)


class SyncBatchNorm(_BatchNormBase):
    """On GSPMD, batch stats are computed over the global (sharded) batch
    automatically when the input is dp-sharded — XLA inserts the cross-chip
    reduction. The reference needs a dedicated NCCL kernel
    (sync_batch_norm_kernel.cu); here the plain op IS sync-BN under jit."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        # structural conversion kept for API parity
        for name, sub in list(layer._sub_layers.items()):
            layer._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        if isinstance(layer, _BatchNormBase) and not isinstance(
                layer, SyncBatchNorm):
            new = SyncBatchNorm(layer._num_features, layer._momentum,
                                layer._epsilon,
                                data_format=layer._data_format)
            new.weight = layer.weight
            new.bias = layer.bias
            new._buffers = layer._buffers
            return new
        return layer


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._num_groups = num_groups
        self._epsilon = epsilon
        self._data_format = data_format
        self.weight = None if weight_attr is False else self.create_parameter(
            shape=[num_channels], attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            shape=[num_channels], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.group_norm(x, self._num_groups, self._epsilon, self.weight,
                            self.bias, self._data_format)


class InstanceNorm1D(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCL",
                 name=None):
        super().__init__()
        self._epsilon = epsilon
        self.scale = None if weight_attr is False else self.create_parameter(
            shape=[num_features], attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            shape=[num_features], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.instance_norm(x, weight=self.scale, bias=self.bias,
                               eps=self._epsilon)


class InstanceNorm2D(InstanceNorm1D):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__(num_features, epsilon, momentum, weight_attr,
                         bias_attr)


class InstanceNorm3D(InstanceNorm1D):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCDHW",
                 name=None):
        super().__init__(num_features, epsilon, momentum, weight_attr,
                         bias_attr)


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0,
                 data_format="NCHW", name=None):
        super().__init__()
        self.args = (size, alpha, beta, k)

    def forward(self, x):
        return F.local_response_norm(x, *self.args)


class SpectralNorm(Layer):
    """Power-iteration spectral norm (reference: nn/layer/norm.py:SpectralNorm)."""

    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12,
                 dtype='float32', name=None):
        super().__init__()
        self._dim = dim
        self._power_iters = power_iters
        self._eps = eps
        h = weight_shape[dim]
        w = int(np.prod(weight_shape)) // h
        self.weight_u = self.create_parameter(
            shape=[h], default_initializer=I.Normal(0, 1.0))
        self.weight_u.stop_gradient = True
        self.weight_v = self.create_parameter(
            shape=[w], default_initializer=I.Normal(0, 1.0))
        self.weight_v.stop_gradient = True

    def forward(self, weight):
        from paddle_tpu.tensor import manipulation as M
        wm = weight
        if self._dim != 0:
            wm = M.transpose(
                wm, [self._dim] + [i for i in range(wm.ndim)
                                   if i != self._dim])
        h = wm.shape[0]
        mat = M.reshape(wm, [h, -1])
        u, v = self.weight_u._value, self.weight_v._value
        for _ in range(self._power_iters):
            v = mat._value.T @ u
            v = v / (jnp.linalg.norm(v) + self._eps)
            u = mat._value @ v
            u = u / (jnp.linalg.norm(u) + self._eps)
        self.weight_u._value = u
        self.weight_v._value = v
        sigma = u @ mat._value @ v
        out = weight / Tensor(sigma)
        return out
