"""MoE layer with stacked expert weights (expert-parallel ready).

Reference: python/paddle/incubate/distributed/models/moe/moe_layer.py:263
MoELayer — per-rank expert sublayers + all-to-all scatter/gather. Here the
experts are ONE set of stacked (E, ...) parameters so the 'ep' mesh axis
shards them declaratively (paddle_tpu.parallel.plan) and a vmap over the
expert dim runs them batched on the MXU; XLA inserts the token all-to-all
from the shardings.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

import paddle_tpu
from paddle_tpu.core.dispatch import defop
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.nn.layer.layers import Layer
from paddle_tpu.nn import initializer as I
from paddle_tpu.nn.functional import moe as FM


@defop("moe_mlp_dropless", amp_policy="white",
       spmd_note="dropless grouped matmul (ragged_dot): expert dim may "
                 "shard over 'ep' (XLA gathers tokens), token dims over "
                 "dp/sp; prefer the capacity path for ep>1 meshes")
def _moe_mlp_dropless(x, router_w, wg, wu, wd, k):
    """Dropless dMoE forward (MegaBlocks semantics; VERDICT r3 item 5 —
    the reference's capacity gate at moe_layer.py:263 silently drops
    overflow tokens; this path honors every token's top-k exactly).
    Returns (out, aux_loss)."""
    lead = x.shape[:-1]
    d = x.shape[-1]
    xt = x.reshape(-1, d)
    logits = jnp.einsum("td,de->te", xt.astype(jnp.float32),
                        router_w.astype(jnp.float32))
    idx, gates, aux = FM.topk_gating_dropless(logits, k)
    out = FM.moe_dropless_mlp(xt, wg, wu, wd, idx, gates)
    return out.reshape(*lead, d), aux


@defop("moe_mlp", amp_policy="white",
       spmd_note="expert dim shards over 'ep'; token dims over dp/sp")
def _moe_mlp(x, router_w, wg, wu, wd, k, capacity_factor):
    """x (..., D) -> (..., D); router_w (D,E); wg/wu (E,D,F); wd (E,F,D).
    Returns (out, aux_loss)."""
    lead = x.shape[:-1]
    d = x.shape[-1]
    xt = x.reshape(-1, d)
    logits = jnp.einsum("td,de->te", xt.astype(jnp.float32),
                        router_w.astype(jnp.float32))
    gate = FM.top2_gating if k == 2 else FM.switch_gating
    combine, dispatch, aux = gate(logits, capacity_factor=capacity_factor)

    expert_in = FM.moe_dispatch(xt, dispatch)            # (E,C,D)

    def expert(w_g, w_u, w_d, h):
        a = jnp.einsum("cd,df->cf", h, w_g)
        b = jnp.einsum("cd,df->cf", h, w_u)
        act = jax.nn.silu(a.astype(jnp.float32)).astype(h.dtype) * b
        return jnp.einsum("cf,fd->cd", act, w_d)

    expert_out = jax.vmap(expert)(wg, wu, wd, expert_in)  # (E,C,D)
    out = FM.moe_combine(expert_out, combine)
    return out.reshape(*lead, d), aux


class MoEMLP(Layer):
    """Drop-in replacement for a dense SwiGLU MLP. Stores the router plus
    stacked expert weights; `aux_loss` is set on every forward and must be
    added to the training loss (Qwen2-MoE/DeepSeekMoE convention)."""

    def __init__(self, hidden_size, intermediate_size, num_experts,
                 top_k=2, capacity_factor=1.25, initializer_range=0.02,
                 dropless=False):
        super().__init__()
        self.num_experts = num_experts
        self.top_k = top_k
        self.capacity_factor = capacity_factor
        self.dropless = dropless
        init = I.Normal(0.0, initializer_range)
        d, f, e = hidden_size, intermediate_size, num_experts
        self.router_weight = self.create_parameter(
            [d, e], default_initializer=init)
        self.experts_gate_weight = self.create_parameter(
            [e, d, f], default_initializer=init)
        self.experts_up_weight = self.create_parameter(
            [e, d, f], default_initializer=init)
        self.experts_down_weight = self.create_parameter(
            [e, f, d], default_initializer=init)
        self.aux_loss = None

    def forward(self, x):
        if self.dropless:
            out, aux = _moe_mlp_dropless(x, self.router_weight,
                                         self.experts_gate_weight,
                                         self.experts_up_weight,
                                         self.experts_down_weight,
                                         k=self.top_k)
        else:
            out, aux = _moe_mlp(x, self.router_weight,
                                self.experts_gate_weight,
                                self.experts_up_weight,
                                self.experts_down_weight,
                                k=self.top_k,
                                capacity_factor=self.capacity_factor)
        self.aux_loss = aux
        return out
