"""MoE layer with stacked expert weights (expert-parallel ready).

Reference: python/paddle/incubate/distributed/models/moe/moe_layer.py:263
MoELayer — per-rank expert sublayers + all-to-all scatter/gather. Here the
experts are ONE set of stacked (E, ...) parameters so the 'ep' mesh axis
shards them declaratively (paddle_tpu.parallel.plan) and a vmap over the
expert dim runs them batched on the MXU; XLA inserts the token all-to-all
from the shardings.
"""
from __future__ import annotations

from contextlib import contextmanager

import jax
import jax.numpy as jnp

import paddle_tpu
from paddle_tpu.core.jax_compat import shard_map
from paddle_tpu.core.dispatch import defop
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.nn.layer.layers import Layer
from paddle_tpu.nn import initializer as I
from paddle_tpu.nn.functional import moe as FM


@defop("moe_mlp_dropless", amp_policy="white",
       spmd_note="dropless grouped matmul (ragged_dot): expert dim may "
                 "shard over 'ep' (XLA gathers tokens), token dims over "
                 "dp/sp; prefer the capacity path for ep>1 meshes")
def _moe_mlp_dropless(x, router_w, wg, wu, wd, k):
    """Dropless dMoE forward (MegaBlocks semantics; VERDICT r3 item 5 —
    the reference's capacity gate at moe_layer.py:263 silently drops
    overflow tokens; this path honors every token's top-k exactly).
    Returns (out, aux_loss)."""
    lead = x.shape[:-1]
    d = x.shape[-1]
    xt = x.reshape(-1, d)
    logits = jnp.einsum("td,de->te", xt.astype(jnp.float32),
                        router_w.astype(jnp.float32))
    idx, gates, aux = FM.topk_gating_dropless(logits, k)
    out = FM.moe_dropless_mlp(xt, wg, wu, wd, idx, gates)
    return out.reshape(*lead, d), aux


# ---------------------------------------------------------------------------
# dropless x expert parallelism (VERDICT r4 item 2)
# ---------------------------------------------------------------------------

_ep_state = {"mesh": None, "axis": "ep", "buffer_rows": None}


@contextmanager
def expert_parallel_guard(mesh, axis="ep", buffer_rows=None):
    """Inside this context, MoEMLP(dropless=True) routes through the
    expert-parallel dropless path: experts shard over the mesh's `axis`,
    tokens exchange via dense-padded all-to-all (reference mechanism:
    global_scatter/global_gather, distributed/utils/moe_utils.py:20).
    Mirrors context_parallel_guard's pattern — active at trace time."""
    prev = dict(_ep_state)
    _ep_state.update(mesh=mesh, axis=axis, buffer_rows=buffer_rows)
    try:
        yield
    finally:
        _ep_state.update(prev)


def current_expert_parallel():
    return dict(_ep_state) if _ep_state["mesh"] is not None else None


def moe_dropless_ep(x, router_w, wg, wu, wd, k, mesh, axis="ep",
                    buffer_rows=None):
    """Global-array wrapper: x (B, S, D) with batch over dp/fsdp and seq
    over `axis` (or (T, D) with tokens over `axis`); expert weights
    (E, ...) sharded over `axis` on dim 0. shard_map is full-manual over
    the mentioned axes only; mp (if any) stays replicated inside (each
    mp member computes identically)."""
    from jax.sharding import PartitionSpec as P
    from paddle_tpu.distributed.mesh import ProcessMesh
    if isinstance(mesh, ProcessMesh):
        mesh = mesh.jax_mesh
    names = mesh.axis_names
    if x.ndim == 3:
        batch = tuple(a for a in ("dp", "fsdp") if a in names)
        x_spec = P(batch if batch else None, axis, None)
    elif x.ndim == 2:
        batch = ()
        x_spec = P(axis, None)
    else:
        raise ValueError(f"moe_dropless_ep expects (B, S, D) or (T, D), "
                         f"got shape {x.shape}")
    w_spec = P(axis)

    def local(xl, rw, wgl, wul, wdl):
        d = xl.shape[-1]
        out, aux = FM.moe_dropless_mlp_ep_local(
            xl.reshape(-1, d), rw, wgl, wul, wdl, k, axis,
            token_axes=batch, buffer_rows=buffer_rows)
        return out.reshape(xl.shape), aux

    fn = shard_map(
        local, mesh=mesh,
        in_specs=(x_spec, P(), w_spec, w_spec, w_spec),
        out_specs=(x_spec, P()), check_vma=False)
    return fn(x, router_w, wg, wu, wd)


@defop("moe_mlp_dropless_ep", amp_policy="white",
       spmd_note="experts shard over 'ep' (dense-padded all-to-all "
                 "dispatch inside shard_map); token dims over dp + ep")
def _moe_mlp_dropless_ep(x, router_w, wg, wu, wd, k, mesh, axis,
                         buffer_rows):
    """Dropless dMoE x expert parallelism (VERDICT r4 item 2; reference
    global_scatter/global_gather, distributed/utils/moe_utils.py:20).
    Returns (out, aux_loss)."""
    return moe_dropless_ep(x, router_w, wg, wu, wd, k, mesh, axis=axis,
                           buffer_rows=buffer_rows)


@defop("moe_mlp", amp_policy="white",
       spmd_note="expert dim shards over 'ep'; token dims over dp/sp")
def _moe_mlp(x, router_w, wg, wu, wd, k, capacity_factor):
    """x (..., D) -> (..., D); router_w (D,E); wg/wu (E,D,F); wd (E,F,D).
    Returns (out, aux_loss)."""
    lead = x.shape[:-1]
    d = x.shape[-1]
    xt = x.reshape(-1, d)
    logits = jnp.einsum("td,de->te", xt.astype(jnp.float32),
                        router_w.astype(jnp.float32))
    gate = FM.top2_gating if k == 2 else FM.switch_gating
    combine, dispatch, aux = gate(logits, capacity_factor=capacity_factor)

    expert_in = FM.moe_dispatch(xt, dispatch)            # (E,C,D)

    def expert(w_g, w_u, w_d, h):
        a = jnp.einsum("cd,df->cf", h, w_g)
        b = jnp.einsum("cd,df->cf", h, w_u)
        act = jax.nn.silu(a.astype(jnp.float32)).astype(h.dtype) * b
        return jnp.einsum("cf,fd->cd", act, w_d)

    expert_out = jax.vmap(expert)(wg, wu, wd, expert_in)  # (E,C,D)
    out = FM.moe_combine(expert_out, combine)
    return out.reshape(*lead, d), aux


class MoEMLP(Layer):
    """Drop-in replacement for a dense SwiGLU MLP. Stores the router plus
    stacked expert weights; `aux_loss` is set on every forward and must be
    added to the training loss (Qwen2-MoE/DeepSeekMoE convention)."""

    def __init__(self, hidden_size, intermediate_size, num_experts,
                 top_k=2, capacity_factor=1.25, initializer_range=0.02,
                 dropless=False):
        super().__init__()
        self.num_experts = num_experts
        self.top_k = top_k
        self.capacity_factor = capacity_factor
        self.dropless = dropless
        init = I.Normal(0.0, initializer_range)
        d, f, e = hidden_size, intermediate_size, num_experts
        self.router_weight = self.create_parameter(
            [d, e], default_initializer=init)
        self.experts_gate_weight = self.create_parameter(
            [e, d, f], default_initializer=init)
        self.experts_up_weight = self.create_parameter(
            [e, d, f], default_initializer=init)
        self.experts_down_weight = self.create_parameter(
            [e, f, d], default_initializer=init)
        self.aux_loss = None

    def forward(self, x):
        if self.dropless:
            ep = current_expert_parallel()
            if ep is not None:
                out, aux = _moe_mlp_dropless_ep(
                    x, self.router_weight, self.experts_gate_weight,
                    self.experts_up_weight, self.experts_down_weight,
                    k=self.top_k, mesh=ep["mesh"], axis=ep["axis"],
                    buffer_rows=ep["buffer_rows"])
                self.aux_loss = aux
                return out
            out, aux = _moe_mlp_dropless(x, self.router_weight,
                                         self.experts_gate_weight,
                                         self.experts_up_weight,
                                         self.experts_down_weight,
                                         k=self.top_k)
        else:
            out, aux = _moe_mlp(x, self.router_weight,
                                self.experts_gate_weight,
                                self.experts_up_weight,
                                self.experts_down_weight,
                                k=self.top_k,
                                capacity_factor=self.capacity_factor)
        self.aux_loss = aux
        return out
