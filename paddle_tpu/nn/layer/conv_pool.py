"""Conv + pooling layers (reference: python/paddle/nn/layer/conv.py,
pooling.py)."""
from __future__ import annotations

import numpy as np

from paddle_tpu.nn import functional as F
from paddle_tpu.nn import initializer as I
from paddle_tpu.nn.layer.layers import Layer


def _tuple(v, n):
    return tuple(v) if isinstance(v, (list, tuple)) else (v,) * n


class _ConvNd(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, n, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 transpose=False, output_padding=0):
        super().__init__()
        self._in_channels = in_channels
        self._out_channels = out_channels
        self._kernel_size = _tuple(kernel_size, n)
        self._stride = stride
        self._padding = padding
        self._dilation = dilation
        self._groups = groups
        self._data_format = data_format
        self._output_padding = output_padding
        self._n = n
        if transpose:
            shape = [in_channels, out_channels // groups] + \
                list(self._kernel_size)
        else:
            shape = [out_channels, in_channels // groups] + \
                list(self._kernel_size)
        fan_in = (in_channels // groups) * int(np.prod(self._kernel_size))
        k = 1.0 / np.sqrt(fan_in)
        self.weight = self.create_parameter(
            shape=shape, attr=weight_attr,
            default_initializer=I.Uniform(-k, k))
        self.bias = self.create_parameter(
            shape=[out_channels], attr=bias_attr, is_bias=True,
            default_initializer=I.Uniform(-k, k)) \
            if bias_attr is not False else None


class Conv1D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, 1, stride,
                         padding, dilation, groups, padding_mode,
                         weight_attr, bias_attr, data_format)

    def forward(self, x):
        return F.conv1d(x, self.weight, self.bias, self._stride,
                        self._padding, self._dilation, self._groups,
                        self._data_format)


class Conv2D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, 2, stride,
                         padding, dilation, groups, padding_mode,
                         weight_attr, bias_attr, data_format)

    def forward(self, x):
        return F.conv2d(x, self.weight, self.bias, self._stride,
                        self._padding, self._dilation, self._groups,
                        self._data_format)

    def extra_repr(self):
        return (f"{self._in_channels}, {self._out_channels}, "
                f"kernel_size={list(self._kernel_size)}, "
                f"stride={self._stride}")


class Conv3D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, 3, stride,
                         padding, dilation, groups, padding_mode,
                         weight_attr, bias_attr, data_format)

    def forward(self, x):
        return F.conv3d(x, self.weight, self.bias, self._stride,
                        self._padding, self._dilation, self._groups,
                        self._data_format)


class Conv1DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, 1, stride,
                         padding, dilation, groups, "zeros", weight_attr,
                         bias_attr, data_format, transpose=True,
                         output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv1d_transpose(x, self.weight, self.bias, self._stride,
                                  self._padding, self._output_padding,
                                  self._groups, self._dilation,
                                  data_format=self._data_format)


class Conv2DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, dilation=1, groups=1,
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, 2, stride,
                         padding, dilation, groups, "zeros", weight_attr,
                         bias_attr, data_format, transpose=True,
                         output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv2d_transpose(x, self.weight, self.bias, self._stride,
                                  self._padding, self._output_padding,
                                  groups=self._groups,
                                  dilation=self._dilation,
                                  data_format=self._data_format)


class Conv3DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, dilation=1, groups=1,
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, 3, stride,
                         padding, dilation, groups, "zeros", weight_attr,
                         bias_attr, data_format, transpose=True,
                         output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv3d_transpose(x, self.weight, self.bias, self._stride,
                                  self._padding, self._output_padding,
                                  self._groups, self._dilation,
                                  data_format=self._data_format)


class MaxPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 return_mask=False, ceil_mode=False, name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding)
        self.return_mask = return_mask

    def forward(self, x):
        return F.max_pool1d(x, self.args[0], self.args[1], self.args[2],
                            return_mask=self.return_mask)


class MaxPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 return_mask=False, ceil_mode=False, data_format="NCHW",
                 name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding)
        self.return_mask = return_mask
        self.ceil_mode = ceil_mode

    def forward(self, x):
        return F.max_pool2d(x, self.args[0], self.args[1], self.args[2],
                            ceil_mode=self.ceil_mode,
                            return_mask=self.return_mask)


class MaxPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 return_mask=False, ceil_mode=False, data_format="NCDHW",
                 name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding)

    def forward(self, x):
        return F.max_pool3d(x, self.args[0], self.args[1], self.args[2])


class AvgPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, exclusive=True,
                 ceil_mode=False, name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding, exclusive)

    def forward(self, x):
        return F.avg_pool1d(x, self.args[0], self.args[1], self.args[2],
                            exclusive=self.args[3])


class AvgPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 exclusive=True, divisor_override=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding, exclusive,
                     divisor_override)
        self.ceil_mode = ceil_mode

    def forward(self, x):
        return F.avg_pool2d(x, self.args[0], self.args[1], self.args[2],
                            ceil_mode=self.ceil_mode, exclusive=self.args[3],
                            divisor_override=self.args[4])


class AvgPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 exclusive=True, divisor_override=None, data_format="NCDHW",
                 name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding)

    def forward(self, x):
        return F.avg_pool3d(x, self.args[0], self.args[1], self.args[2])


class AdaptiveAvgPool1D(Layer):
    def __init__(self, output_size, name=None):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_avg_pool1d(x, self.output_size)


class AdaptiveAvgPool2D(Layer):
    def __init__(self, output_size, data_format="NCHW", name=None):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_avg_pool2d(x, self.output_size)


class AdaptiveAvgPool3D(Layer):
    def __init__(self, output_size, data_format="NCDHW", name=None):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_avg_pool3d(x, self.output_size)


class AdaptiveMaxPool1D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_max_pool1d(x, self.output_size)


class AdaptiveMaxPool2D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_max_pool2d(x, self.output_size)
