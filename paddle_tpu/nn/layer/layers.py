"""nn.Layer base class.

TPU-native rebuild of the reference Layer (reference:
python/paddle/nn/layer/layers.py:334 — parameters/buffers registration via
__setattr__, forward pre/post hooks, state_dict/set_state_dict, train/eval,
apply, to). Parameters are paddle_tpu Parameters (mutable handles over
jax.Array) so the same Layer object serves eager training, jit tracing
(via jit.functional state swapping), and GSPMD sharding (parameters are
device_put with NamedSharding in place).
"""
from __future__ import annotations

import collections
from typing import Iterator

import numpy as np
import jax.numpy as jnp

from paddle_tpu.core import dtype as dtypes
from paddle_tpu.core.tensor import Tensor, Parameter
from paddle_tpu.nn import initializer as init_mod


class Layer:
    def __init__(self, name_scope=None, dtype="float32"):
        object.__setattr__(self, "_parameters", collections.OrderedDict())
        object.__setattr__(self, "_sub_layers", collections.OrderedDict())
        object.__setattr__(self, "_buffers", collections.OrderedDict())
        object.__setattr__(self, "_non_persistable_buffer_names", set())
        self._forward_pre_hooks = collections.OrderedDict()
        self._forward_post_hooks = collections.OrderedDict()
        self.training = True
        self._dtype = dtypes.convert_dtype(dtype)
        self._name_scope = name_scope or self.__class__.__name__.lower()

    # -- attribute magic ---------------------------------------------------
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call super().__init__() first")
            params[name] = value
            layers.pop(name, None)
            buffers.pop(name, None) if buffers else None
            self.__dict__.pop(name, None)
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call super().__init__() first")
            layers[name] = value
            params.pop(name, None)
            self.__dict__.pop(name, None)
        elif buffers is not None and name in buffers:
            if value is None or isinstance(value, Tensor):
                buffers[name] = value
            else:
                object.__setattr__(self, name, value)
        else:
            if params is not None and name in params:
                if value is None:
                    del params[name]
                    object.__setattr__(self, name, None)
                    return
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(
            f"'{type(self).__name__}' object has no attribute '{name}'")

    def __delattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    def __dir__(self):
        return list(super().__dir__()) + list(self._parameters) + \
            list(self._sub_layers) + list(self._buffers)

    # -- construction helpers ----------------------------------------------
    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None):
        """Reference: layers.py create_parameter — ParamAttr-driven."""
        dt = dtypes.convert_dtype(dtype) or self._dtype
        initializer = None
        name = None
        trainable = True
        if attr is not None and attr is not False:
            initializer = getattr(attr, "initializer", None)
            name = getattr(attr, "name", None)
            trainable = getattr(attr, "trainable", True)
        if attr is False:
            return None
        if initializer is None:
            initializer = default_initializer
        if initializer is None:
            initializer = (init_mod.Constant(0.0) if is_bias
                           else init_mod.XavierUniform())
        arr = initializer(tuple(int(s) for s in shape), dt)
        return Parameter(arr, name=name, trainable=trainable)

    def add_parameter(self, name, parameter):
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name, sublayer):
        self._sub_layers[str(name)] = sublayer
        return sublayer

    def register_buffer(self, name, tensor, persistable=True):
        """Reference: layers.py register_buffer."""
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        return tensor

    # -- iteration ---------------------------------------------------------
    def parameters(self, include_sublayers=True):
        return [p for _, p in self.named_parameters(
            include_sublayers=include_sublayers)]

    def named_parameters(self, prefix="", include_sublayers=True):
        seen = set()
        for name, layer in self._walk(prefix, include_sublayers):
            for pname, p in layer._parameters.items():
                if p is not None and id(p) not in seen:
                    seen.add(id(p))
                    yield (f"{name}.{pname}" if name else pname), p

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers(
            include_sublayers=include_sublayers)]

    def named_buffers(self, prefix="", include_sublayers=True):
        seen = set()
        for name, layer in self._walk(prefix, include_sublayers):
            for bname, b in layer._buffers.items():
                if b is not None and id(b) not in seen:
                    seen.add(id(b))
                    yield (f"{name}.{bname}" if name else bname), b

    def _walk(self, prefix="", include_sublayers=True):
        yield prefix, self
        if include_sublayers:
            for lname, sub in self._sub_layers.items():
                if sub is None:
                    continue
                sub_prefix = f"{prefix}.{lname}" if prefix else lname
                yield from sub._walk(sub_prefix, True)

    def sublayers(self, include_self=False):
        out = [l for _, l in self._walk()] if include_self else \
            [l for n, l in self._walk() if n != ""]
        return out

    def named_sublayers(self, prefix="", include_self=False, layers_set=None):
        for n, l in self._walk(prefix):
            if n == prefix and not include_self:
                continue
            yield n, l

    def children(self):
        return iter(self._sub_layers.values())

    def named_children(self):
        return iter(self._sub_layers.items())

    def apply(self, fn):
        for l in self.sublayers(include_self=True):
            fn(l)
        return self

    # -- mode --------------------------------------------------------------
    def train(self):
        for l in self.sublayers(include_self=True):
            l.training = True
        return self

    def eval(self):
        for l in self.sublayers(include_self=True):
            l.training = False
        return self

    # -- state dict --------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers=True,
                   structured_name_prefix="", use_hook=True):
        out = destination if destination is not None else collections.OrderedDict()
        for name, p in self.named_parameters(
                prefix=structured_name_prefix,
                include_sublayers=include_sublayers):
            out[name] = p
        for name, layer in self._walk(structured_name_prefix,
                                      include_sublayers):
            for bname, b in layer._buffers.items():
                if b is not None and bname not in \
                        layer._non_persistable_buffer_names:
                    out[(f"{name}.{bname}" if name else bname)] = b
        return out

    def set_state_dict(self, state_dict, use_structured_name=True):
        """Reference: layers.py set_state_dict — copy by name, cast dtype."""
        own = self.state_dict()
        missing = []
        for name, t in own.items():
            if name not in state_dict:
                missing.append(name)
                continue
            src = state_dict[name]
            arr = src._value if isinstance(src, Tensor) else jnp.asarray(src)
            if tuple(arr.shape) != tuple(t.shape):
                raise ValueError(
                    f"shape mismatch for {name}: loaded {tuple(arr.shape)} "
                    f"vs expected {tuple(t.shape)}")
            t._value = arr.astype(t._value.dtype)
        unexpected = [k for k in state_dict if k not in own]
        return missing, unexpected

    load_dict = set_state_dict
    set_dict = set_state_dict

    # -- dtype / placement -------------------------------------------------
    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            dt = dtypes.convert_dtype(dtype)
            for t in list(self.parameters()) + list(self.buffers()):
                if dtypes.is_floating_point(t.dtype):
                    t._value = t._value.astype(dt)
            for l in self.sublayers(include_self=True):
                l._dtype = dt
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def float(self):
        return self.to(dtype="float32")

    def half(self):
        return self.to(dtype="float16")

    def bfloat16(self):
        return self.to(dtype="bfloat16")

    # -- hooks -------------------------------------------------------------
    def register_forward_pre_hook(self, hook):
        handle = _HookRemoveHelper(self._forward_pre_hooks)
        self._forward_pre_hooks[handle._id] = hook
        return handle

    def register_forward_post_hook(self, hook):
        handle = _HookRemoveHelper(self._forward_post_hooks)
        self._forward_post_hooks[handle._id] = hook
        return handle

    # -- call --------------------------------------------------------------
    def forward(self, *args, **kwargs):
        raise NotImplementedError

    def __call__(self, *args, **kwargs):
        for hook in self._forward_pre_hooks.values():
            res = hook(self, args)
            if res is not None:
                args = res if isinstance(res, tuple) else (res,)
        out = self.forward(*args, **kwargs)
        for hook in self._forward_post_hooks.values():
            res = hook(self, args, out)
            if res is not None:
                out = res
        return out

    def extra_repr(self):
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, sub in self._sub_layers.items():
            sub_repr = repr(sub).split("\n")
            sub_repr = [sub_repr[0]] + ["  " + l for l in sub_repr[1:]]
            lines.append(f"  ({name}): " + "\n".join(sub_repr))
        main = f"{self.__class__.__name__}({extra}"
        if lines:
            return main + "\n" + "\n".join(lines) + "\n)"
        return main + ")"

    def full_name(self):
        return self._name_scope


class _HookRemoveHelper:
    _next_id = [0]

    def __init__(self, hooks_dict):
        self._hooks = hooks_dict
        self._id = _HookRemoveHelper._next_id[0]
        _HookRemoveHelper._next_id[0] += 1

    def remove(self):
        self._hooks.pop(self._id, None)


class ParamAttr:
    """Reference: python/paddle/base/param_attr.py ParamAttr."""

    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, do_model_average=True,
                 need_clip=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.need_clip = need_clip
