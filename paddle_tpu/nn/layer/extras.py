"""Long-tail nn layers wrapping functional.extras (reference:
python/paddle/nn/layer/{pooling,loss,common,rnn}.py remainder).
"""
from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.nn.layer.layers import Layer
from paddle_tpu.nn import functional as F

__all__ = [
    'AdaptiveMaxPool3D', 'FractionalMaxPool2D', 'FractionalMaxPool3D',
    'MaxUnPool1D', 'MaxUnPool2D', 'MaxUnPool3D', 'CTCLoss',
    'GaussianNLLLoss', 'HSigmoidLoss', 'MultiLabelSoftMarginLoss',
    'MultiMarginLoss', 'PoissonNLLLoss', 'RNNTLoss', 'SoftMarginLoss',
    'TripletMarginWithDistanceLoss', 'Unflatten', 'BeamSearchDecoder',
    'dynamic_decode',
]


class AdaptiveMaxPool3D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self._output_size = output_size
        self._return_mask = return_mask

    def forward(self, x):
        return F.adaptive_max_pool3d(x, self._output_size,
                                     self._return_mask)


class FractionalMaxPool2D(Layer):
    def __init__(self, output_size, kernel_size=None, random_u=None,
                 return_mask=False, name=None):
        super().__init__()
        self._args = (output_size, kernel_size, random_u, return_mask)

    def forward(self, x):
        return F.fractional_max_pool2d(x, *self._args)


class FractionalMaxPool3D(Layer):
    def __init__(self, output_size, kernel_size=None, random_u=None,
                 return_mask=False, name=None):
        super().__init__()
        self._args = (output_size, kernel_size, random_u, return_mask)

    def forward(self, x):
        return F.fractional_max_pool3d(x, *self._args)


class _MaxUnPoolNd(Layer):
    _fn = None

    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format=None, output_size=None, name=None):
        super().__init__()
        self._kernel_size = kernel_size
        self._stride = stride
        self._padding = padding
        self._output_size = output_size

    def forward(self, x, indices):
        return type(self)._fn(x, indices, self._kernel_size, self._stride,
                              self._padding,
                              output_size=self._output_size)


class MaxUnPool1D(_MaxUnPoolNd):
    _fn = staticmethod(F.max_unpool1d)

    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format='NCL', output_size=None, name=None):
        super().__init__(kernel_size, stride, padding, data_format,
                         output_size, name)


class MaxUnPool2D(_MaxUnPoolNd):
    _fn = staticmethod(F.max_unpool2d)

    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format='NCHW', output_size=None, name=None):
        super().__init__(kernel_size, stride, padding, data_format,
                         output_size, name)


class MaxUnPool3D(_MaxUnPoolNd):
    _fn = staticmethod(F.max_unpool3d)

    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format='NCDHW', output_size=None, name=None):
        super().__init__(kernel_size, stride, padding, data_format,
                         output_size, name)


class CTCLoss(Layer):
    def __init__(self, blank=0, reduction="mean"):
        super().__init__()
        self.blank, self.reduction = blank, reduction

    def forward(self, log_probs, labels, input_lengths, label_lengths,
                norm_by_times=False):
        return F.ctc_loss(log_probs, labels, input_lengths, label_lengths,
                          blank=self.blank, reduction=self.reduction,
                          norm_by_times=norm_by_times)


class GaussianNLLLoss(Layer):
    def __init__(self, full=False, epsilon=1e-6, reduction="mean",
                 name=None):
        super().__init__()
        self._args = (full, epsilon, reduction)

    def forward(self, input, label, variance):
        full, eps, red = self._args
        return F.gaussian_nll_loss(input, label, variance, full, eps, red)


class HSigmoidLoss(Layer):
    """(reference: nn/layer/loss.py HSigmoidLoss — owns the path weights)."""

    def __init__(self, feature_size, num_classes, weight_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False,
                 name=None):
        super().__init__()
        if is_custom:
            raise NotImplementedError("custom trees not supported")
        self.num_classes = num_classes
        self.weight = self.create_parameter(
            [num_classes, feature_size], attr=weight_attr)
        self.bias = self.create_parameter([num_classes], attr=bias_attr,
                                          is_bias=True)

    def forward(self, input, label):
        return F.hsigmoid_loss(input, label, self.num_classes, self.weight,
                               self.bias)


class MultiLabelSoftMarginLoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self._weight, self._reduction = weight, reduction

    def forward(self, input, label):
        return F.multi_label_soft_margin_loss(input, label, self._weight,
                                              self._reduction)


class MultiMarginLoss(Layer):
    def __init__(self, p=1, margin=1.0, weight=None, reduction="mean",
                 name=None):
        super().__init__()
        self._args = (p, margin, weight, reduction)

    def forward(self, input, label):
        p, m, w, r = self._args
        return F.multi_margin_loss(input, label, p, m, w, r)


class PoissonNLLLoss(Layer):
    def __init__(self, log_input=True, full=False, epsilon=1e-8,
                 reduction="mean", name=None):
        super().__init__()
        self._args = (log_input, full, epsilon, reduction)

    def forward(self, input, label):
        li, fu, ep, re = self._args
        return F.poisson_nll_loss(input, label, li, fu, ep, re)


class RNNTLoss(Layer):
    def __init__(self, blank=0, fastemit_lambda=0.001, reduction="mean",
                 name=None):
        super().__init__()
        self._args = (blank, fastemit_lambda, reduction)

    def forward(self, input, label, input_lengths, label_lengths):
        b, fe, r = self._args
        return F.rnnt_loss(input, label, input_lengths, label_lengths,
                           blank=b, fastemit_lambda=fe, reduction=r)


class SoftMarginLoss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self._reduction = reduction

    def forward(self, input, label):
        return F.soft_margin_loss(input, label, self._reduction)


class TripletMarginWithDistanceLoss(Layer):
    def __init__(self, distance_function=None, margin=1.0, swap=False,
                 reduction="mean", name=None):
        super().__init__()
        self._args = (distance_function, margin, swap, reduction)

    def forward(self, input, positive, negative):
        d, m, s, r = self._args
        return F.triplet_margin_with_distance_loss(input, positive,
                                                   negative, d, m, s, r)


class Unflatten(Layer):
    def __init__(self, axis, shape, name=None):
        super().__init__()
        self._axis, self._shape = axis, shape

    def forward(self, x):
        from paddle_tpu import tensor as T
        return T.unflatten(x, self._axis, self._shape)


class BeamSearchDecoder:
    """Greedy/beam decoding driver (reference: nn/decode.py
    BeamSearchDecoder over RNN cells). Compact TPU version: the loop in
    dynamic_decode is host-side (decode is interactive/eval, not a hot
    training path); each step's cell call is jitted as usual."""

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = start_token
        self.end_token = end_token
        self.beam_size = beam_size
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn


def dynamic_decode(decoder, inits=None, max_step_num=None,
                   output_time_major=False, impute_finished=False,
                   is_test=False, return_length=False, **kwargs):
    """Greedy decode loop over a BeamSearchDecoder's cell (reference:
    nn/decode.py dynamic_decode; beam_size=1 greedy semantics).
    max_step_num=None decodes until every row emits end_token, with a
    1000-step safety bound (the reference loops unboundedly)."""
    import numpy as np
    from paddle_tpu import tensor as T
    cell, emb = decoder.cell, decoder.embedding_fn
    state = inits
    token = decoder.start_token
    outputs = []
    finished = None
    lengths = None
    for _ in range(1000 if max_step_num is None else max_step_num):
        inp = emb(token) if emb is not None else token
        out, state = cell(inp, state)
        logits = decoder.output_fn(out) if decoder.output_fn else out
        token = T.argmax(logits, axis=-1)
        tok_np = np.asarray(token._value)
        done_now = (tok_np == decoder.end_token)
        if finished is None:
            finished = np.zeros_like(tok_np, dtype=bool)
            lengths = np.zeros_like(tok_np, dtype=np.int64)
        # a row still live at this step's start emits a real token
        # (its eos, if this is the step it finishes, counts)
        lengths = lengths + (~finished)
        finished = finished | done_now
        # finished sequences keep emitting end_token, not garbage
        if finished.any():
            token = Tensor(jnp.where(jnp.asarray(finished),
                                     decoder.end_token, token._value))
        outputs.append(token)
        if finished.all():
            break
    stacked = T.stack(outputs, axis=0 if output_time_major else 1)
    if return_length:
        return stacked, state, Tensor(jnp.asarray(lengths, jnp.int64))
    return stacked, state
