"""`paddle.linalg` namespace (reference: python/paddle/linalg.py).

Pure re-export of the linear-algebra ops implemented in
paddle_tpu.tensor.linalg — all of them lower to XLA dot_general /
batched LAPACK custom-calls, which XLA schedules onto the MXU where
possible.
"""
from paddle_tpu.tensor.linalg import (  # noqa: F401
    cholesky,
    cholesky_solve,
    cond,
    corrcoef,
    cov,
    det,
    eig,
    eigh,
    eigvals,
    eigvalsh,
    householder_product,
    lstsq,
    lu,
    lu_unpack,
    matrix_exp,
    matrix_norm,
    matrix_power,
    matrix_rank,
    multi_dot,
    norm,
    pca_lowrank,
    pinv,
    qr,
    slogdet,
    solve,
    svd,
    triangular_solve,
    vector_norm,
)
from paddle_tpu.tensor.linalg import inverse as inv  # noqa: F401

__all__ = [
    'cholesky', 'norm', 'matrix_norm', 'vector_norm', 'cond', 'cov',
    'corrcoef', 'inv', 'eig', 'eigvals', 'multi_dot', 'matrix_rank',
    'svd', 'qr', 'householder_product', 'pca_lowrank', 'lu', 'lu_unpack',
    'matrix_exp', 'matrix_power', 'det', 'slogdet', 'eigh', 'eigvalsh',
    'pinv', 'solve', 'cholesky_solve', 'triangular_solve', 'lstsq',
]
