"""KL divergence registry.

Reference: python/paddle/distribution/kl.py — `register_kl(P, Q)` decorator
plus `kl_divergence(p, q)` dispatch with most-specific-match resolution,
and closed forms for the standard pairs.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax.scipy import special as jsp

from paddle_tpu.core.tensor import Tensor
from . import _util as U
from .continuous import (Beta, Cauchy, Exponential, Gamma, Gumbel, Laplace,
                         LogNormal, Normal, Uniform)
from .discrete import Bernoulli, Categorical, Geometric, Poisson
from .distribution import Distribution
from .multivariate import Dirichlet, MultivariateNormal
from .transformed_distribution import Independent

_REGISTRY: dict[tuple[type, type], callable] = {}


def register_kl(cls_p, cls_q):
    """Decorator registering a pairwise KL implementation."""

    def deco(fn):
        _REGISTRY[(cls_p, cls_q)] = fn
        return fn

    return deco


def _lookup(pt, qt):
    matches = [(p, q) for (p, q) in _REGISTRY
               if issubclass(pt, p) and issubclass(qt, q)]
    if not matches:
        return None
    # most specific match: minimal by MRO distance (left-biased like the
    # reference's total ordering)
    def depth(pair):
        p, q = pair
        return (pt.__mro__.index(p), qt.__mro__.index(q))
    return _REGISTRY[min(matches, key=depth)]


def kl_divergence(p, q):
    fn = _lookup(type(p), type(q))
    if fn is None:
        raise NotImplementedError(
            f"No KL(p || q) registered for ({type(p).__name__}, "
            f"{type(q).__name__})")
    return fn(p, q)


@register_kl(Normal, Normal)
def _kl_normal_normal(p, q):
    def f(l1, s1, l2, s2):
        vr = (s1 / s2) ** 2
        return 0.5 * (vr + ((l1 - l2) / s2) ** 2 - 1 - jnp.log(vr))
    return U.op("kl_normal_normal", f, p.loc, p.scale, q.loc, q.scale)


@register_kl(Uniform, Uniform)
def _kl_uniform_uniform(p, q):
    def f(a1, b1, a2, b2):
        res = jnp.log((b2 - a2) / (b1 - a1))
        return jnp.where((a2 <= a1) & (b1 <= b2), res, jnp.inf)
    return U.op("kl_uniform_uniform", f, p.low, p.high, q.low, q.high)


@register_kl(Bernoulli, Bernoulli)
def _kl_bernoulli_bernoulli(p, q):
    def f(p1, p2):
        t1 = p1 * (jnp.log(p1) - jnp.log(p2))
        t2 = (1 - p1) * (jnp.log1p(-p1) - jnp.log1p(-p2))
        return t1 + t2
    return U.op("kl_bern_bern", f, p.probs, q.probs)


@register_kl(Categorical, Categorical)
def _kl_categorical_categorical(p, q):
    def f(lg1, lg2):
        lp1 = jax.nn.log_softmax(lg1, -1)
        lp2 = jax.nn.log_softmax(lg2, -1)
        return jnp.sum(jnp.exp(lp1) * (lp1 - lp2), -1)
    return U.op("kl_cat_cat", f, p.logits, q.logits)


@register_kl(Beta, Beta)
def _kl_beta_beta(p, q):
    def f(a1, b1, a2, b2):
        t1 = jsp.betaln(a2, b2) - jsp.betaln(a1, b1)
        return (t1 + (a1 - a2) * jsp.digamma(a1)
                + (b1 - b2) * jsp.digamma(b1)
                + (a2 - a1 + b2 - b1) * jsp.digamma(a1 + b1))
    return U.op("kl_beta_beta", f, p.alpha, p.beta, q.alpha, q.beta)


@register_kl(Dirichlet, Dirichlet)
def _kl_dirichlet_dirichlet(p, q):
    def f(c1, c2):
        s1 = jnp.sum(c1, -1)
        return (jsp.gammaln(s1) - jnp.sum(jsp.gammaln(c1), -1)
                - jsp.gammaln(jnp.sum(c2, -1))
                + jnp.sum(jsp.gammaln(c2), -1)
                + jnp.sum((c1 - c2) * (jsp.digamma(c1)
                                       - jsp.digamma(s1)[..., None]), -1))
    return U.op("kl_dir_dir", f, p.concentration, q.concentration)


@register_kl(Gamma, Gamma)
def _kl_gamma_gamma(p, q):
    def f(a1, r1, a2, r2):
        return ((a1 - a2) * jsp.digamma(a1) - jsp.gammaln(a1)
                + jsp.gammaln(a2) + a2 * (jnp.log(r1) - jnp.log(r2))
                + a1 * (r2 / r1 - 1))
    return U.op("kl_gamma_gamma", f, p.concentration, p.rate,
                q.concentration, q.rate)


@register_kl(Exponential, Exponential)
def _kl_exponential_exponential(p, q):
    def f(r1, r2):
        rr = r2 / r1
        return rr - 1 - jnp.log(rr)
    return U.op("kl_exp_exp", f, p.rate, q.rate)


@register_kl(Geometric, Geometric)
def _kl_geometric_geometric(p, q):
    def f(p1, p2):
        return (p1 * jnp.log(p1 / p2)
                + (1.0 - p1) * jnp.log((1.0 - p1) / (1.0 - p2))) / p1
    return U.op("kl_geom_geom", f, p.probs, q.probs)


@register_kl(Laplace, Laplace)
def _kl_laplace_laplace(p, q):
    def f(l1, s1, l2, s2):
        d = jnp.abs(l1 - l2)
        return (jnp.log(s2 / s1) + (s1 * jnp.exp(-d / s1) + d) / s2 - 1)
    return U.op("kl_laplace_laplace", f, p.loc, p.scale, q.loc, q.scale)


@register_kl(Poisson, Poisson)
def _kl_poisson_poisson(p, q):
    def f(r1, r2):
        return r1 * (jnp.log(r1) - jnp.log(r2)) - r1 + r2
    return U.op("kl_poisson_poisson", f, p.rate, q.rate)


@register_kl(Gumbel, Gumbel)
def _kl_gumbel_gumbel(p, q):
    """No closed form for general scales; Monte-Carlo estimate of
    E_p[log p - log q] (the reference evaluates the same way)."""
    samples = p.rsample((256,))
    from paddle_tpu import tensor as T
    return T.mean(T.subtract(p.log_prob(samples), q.log_prob(samples)),
                  axis=0)


@register_kl(LogNormal, LogNormal)
def _kl_lognormal_lognormal(p, q):
    # equals the KL of the underlying normals; delegate so any fix to the
    # Normal closed form applies here too
    return _kl_normal_normal(Normal(p.loc, p.scale), Normal(q.loc, q.scale))


@register_kl(MultivariateNormal, MultivariateNormal)
def _kl_mvn_mvn(p, q):
    def f(l1, L1, l2, L2):
        d = L1.shape[-1]
        # tr(S2^-1 S1) = ||L2^-1 L1||_F^2 via triangular solve
        M = jax.scipy.linalg.solve_triangular(
            jnp.broadcast_to(L2, jnp.broadcast_shapes(jnp.shape(L1),
                                                      jnp.shape(L2))),
            jnp.broadcast_to(L1, jnp.broadcast_shapes(jnp.shape(L1),
                                                      jnp.shape(L2))),
            lower=True)
        tr = jnp.sum(M * M, axis=(-2, -1))
        diff = l2 - l1
        y = jax.scipy.linalg.solve_triangular(
            jnp.broadcast_to(
                L2, jnp.broadcast_shapes(
                    jnp.shape(L2), jnp.shape(diff)[:-1] + jnp.shape(L2)[-2:]
                )), diff[..., None], lower=True)[..., 0]
        maha = jnp.sum(y * y, -1)
        logdet1 = jnp.sum(jnp.log(jnp.diagonal(L1, axis1=-2, axis2=-1)), -1)
        logdet2 = jnp.sum(jnp.log(jnp.diagonal(L2, axis1=-2, axis2=-1)), -1)
        return 0.5 * (tr + maha - d) + logdet2 - logdet1
    return U.op("kl_mvn_mvn", f, p.loc, p.scale_tril, q.loc, q.scale_tril)


@register_kl(Cauchy, Cauchy)
def _kl_cauchy_cauchy(p, q):
    def f(l1, s1, l2, s2):
        # closed form (Chyzak & Nielsen 2019)
        return jnp.log(((s1 + s2) ** 2 + (l1 - l2) ** 2)
                       / (4 * s1 * s2))
    return U.op("kl_cauchy_cauchy", f, p.loc, p.scale, q.loc, q.scale)


@register_kl(Independent, Independent)
def _kl_independent_independent(p, q):
    if p.reinterpreted_batch_rank != q.reinterpreted_batch_rank:
        raise NotImplementedError(
            "Independent KL requires equal reinterpreted ranks")
    inner = kl_divergence(p.base, q.base)
    n = p.reinterpreted_batch_rank
    return U.op("kl_independent_sum", lambda a: jnp.sum(
        a, axis=tuple(range(a.ndim - n, a.ndim))), inner)
