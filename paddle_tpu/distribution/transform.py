"""Bijective transforms.

Reference: python/paddle/distribution/transform.py (Transform, Chain/
Affine/Abs/Exp/Power/Reshape/Sigmoid/Softmax/Stack/StickBreaking/Tanh/
Independent transforms). Implemented over jnp through the eager dispatcher
so forward/inverse/log-det are differentiable.
"""
from __future__ import annotations

import math
from functools import reduce
import operator

import jax
import jax.numpy as jnp

from paddle_tpu.core.tensor import Tensor
from . import _util as U

__all__ = [
    "Transform", "AbsTransform", "AffineTransform", "ChainTransform",
    "ExpTransform", "IndependentTransform", "PowerTransform",
    "ReshapeTransform", "SigmoidTransform", "SoftmaxTransform",
    "StackTransform", "StickBreakingTransform", "TanhTransform",
]


class Type:
    BIJECTION = "bijection"
    INJECTION = "injection"
    SURJECTION = "surjection"
    OTHER = "other"

    @classmethod
    def is_injective(cls, t):
        return t in (cls.BIJECTION, cls.INJECTION)


class Transform:
    _type = Type.INJECTION

    @property
    def type(self):
        return self._type

    def __call__(self, input):
        from .transformed_distribution import TransformedDistribution
        from .distribution import Distribution
        if isinstance(input, Distribution):
            return TransformedDistribution(input, [self])
        if isinstance(input, Transform):
            return ChainTransform([self, input])
        return self.forward(input)

    def forward(self, x):
        return U.op(f"tfm_fwd_{type(self).__name__}",
                    self._forward, U.value_arr(x))

    def inverse(self, y):
        return U.op(f"tfm_inv_{type(self).__name__}",
                    self._inverse, U.value_arr(y))

    def forward_log_det_jacobian(self, x):
        return U.op(f"tfm_fldj_{type(self).__name__}",
                    self._forward_log_det_jacobian, U.value_arr(x))

    def inverse_log_det_jacobian(self, y):
        return U.op(f"tfm_ildj_{type(self).__name__}",
                    self._inverse_log_det_jacobian, U.value_arr(y))

    def forward_shape(self, shape):
        return tuple(shape)

    def inverse_shape(self, shape):
        return tuple(shape)

    # event dims consumed by this transform
    _domain_event_dim = 0
    _codomain_event_dim = 0

    def _forward(self, x):
        raise NotImplementedError

    def _inverse(self, y):
        raise NotImplementedError

    def _forward_log_det_jacobian(self, x):
        raise NotImplementedError

    def _inverse_log_det_jacobian(self, y):
        return -self._forward_log_det_jacobian(self._inverse(y))


class AbsTransform(Transform):
    _type = Type.SURJECTION

    def _forward(self, x):
        return jnp.abs(x)

    def _inverse(self, y):
        return y  # right-inverse (positive branch), as in the reference


class AffineTransform(Transform):
    _type = Type.BIJECTION

    def __init__(self, loc, scale):
        self.loc, self.scale = loc, scale

    def forward(self, x):
        return U.op("affine_fwd", lambda x, l, s: l + s * x,
                    U.value_arr(x), self.loc, self.scale)

    def inverse(self, y):
        return U.op("affine_inv", lambda y, l, s: (y - l) / s,
                    U.value_arr(y), self.loc, self.scale)

    def forward_log_det_jacobian(self, x):
        return U.op(
            "affine_fldj",
            lambda x, s: jnp.broadcast_to(
                jnp.log(jnp.abs(s)),
                jnp.broadcast_shapes(jnp.shape(x), jnp.shape(s))),
            U.value_arr(x), self.scale)

    def inverse_log_det_jacobian(self, y):
        return U.op(
            "affine_ildj",
            lambda y, s: jnp.broadcast_to(
                -jnp.log(jnp.abs(s)),
                jnp.broadcast_shapes(jnp.shape(y), jnp.shape(s))),
            U.value_arr(y), self.scale)


class ExpTransform(Transform):
    _type = Type.BIJECTION

    def _forward(self, x):
        return jnp.exp(x)

    def _inverse(self, y):
        return jnp.log(y)

    def _forward_log_det_jacobian(self, x):
        return x


class PowerTransform(Transform):
    _type = Type.BIJECTION

    def __init__(self, power):
        self.power = power

    def forward(self, x):
        return U.op("power_fwd", lambda x, p: jnp.power(x, p),
                    U.value_arr(x), self.power)

    def inverse(self, y):
        return U.op("power_inv", lambda y, p: jnp.power(y, 1.0 / p),
                    U.value_arr(y), self.power)

    def forward_log_det_jacobian(self, x):
        return U.op(
            "power_fldj",
            lambda x, p: jnp.log(jnp.abs(p * jnp.power(x, p - 1))),
            U.value_arr(x), self.power)

    def inverse_log_det_jacobian(self, y):
        return U.op(
            "power_ildj",
            lambda y, p: -jnp.log(jnp.abs(
                p * jnp.power(jnp.power(y, 1.0 / p), p - 1))),
            U.value_arr(y), self.power)


class SigmoidTransform(Transform):
    _type = Type.BIJECTION

    def _forward(self, x):
        return jax.nn.sigmoid(x)

    def _inverse(self, y):
        return jnp.log(y) - jnp.log1p(-y)

    def _forward_log_det_jacobian(self, x):
        return -jax.nn.softplus(-x) - jax.nn.softplus(x)


class TanhTransform(Transform):
    _type = Type.BIJECTION

    def _forward(self, x):
        return jnp.tanh(x)

    def _inverse(self, y):
        return jnp.arctanh(y)

    def _forward_log_det_jacobian(self, x):
        return 2.0 * (math.log(2.0) - x - jax.nn.softplus(-2.0 * x))


class SoftmaxTransform(Transform):
    _type = Type.OTHER
    _domain_event_dim = 1
    _codomain_event_dim = 1

    def _forward(self, x):
        return jax.nn.softmax(x, axis=-1)

    def _inverse(self, y):
        return jnp.log(y)


class ReshapeTransform(Transform):
    _type = Type.BIJECTION

    def __init__(self, in_event_shape, out_event_shape):
        self.in_event_shape = tuple(in_event_shape)
        self.out_event_shape = tuple(out_event_shape)
        if reduce(operator.mul, self.in_event_shape, 1) != \
                reduce(operator.mul, self.out_event_shape, 1):
            raise ValueError("in/out event sizes must match")
        self._domain_event_dim = len(self.in_event_shape)
        self._codomain_event_dim = len(self.out_event_shape)

    def _forward(self, x):
        batch = x.shape[:x.ndim - len(self.in_event_shape)]
        return x.reshape(batch + self.out_event_shape)

    def _inverse(self, y):
        batch = y.shape[:y.ndim - len(self.out_event_shape)]
        return y.reshape(batch + self.in_event_shape)

    def _forward_log_det_jacobian(self, x):
        batch = x.shape[:x.ndim - len(self.in_event_shape)]
        return jnp.zeros(batch, x.dtype)

    def forward_shape(self, shape):
        n = len(self.in_event_shape)
        if tuple(shape[len(shape) - n:]) != self.in_event_shape:
            raise ValueError("shape mismatch in ReshapeTransform")
        return tuple(shape[:len(shape) - n]) + self.out_event_shape

    def inverse_shape(self, shape):
        n = len(self.out_event_shape)
        return tuple(shape[:len(shape) - n]) + self.in_event_shape


class StickBreakingTransform(Transform):
    """R^{K-1} -> K-simplex via stick breaking. Reference:
    transform.py StickBreakingTransform."""
    _type = Type.BIJECTION
    _domain_event_dim = 1
    _codomain_event_dim = 1

    def _forward(self, x):
        offset = x.shape[-1] + 1 - jnp.arange(1, x.shape[-1] + 1)
        z = jax.nn.sigmoid(x - jnp.log(offset.astype(x.dtype)))
        zc = jnp.cumprod(1 - z, axis=-1)
        pad = jnp.ones(x.shape[:-1] + (1,), x.dtype)
        return jnp.concatenate([z, pad], -1) * \
            jnp.concatenate([pad, zc], -1)

    def _inverse(self, y):
        y_crop = y[..., :-1]
        offset = y.shape[-1] - jnp.arange(1, y.shape[-1])
        sf = 1 - jnp.cumsum(y_crop, axis=-1)
        sf = jnp.concatenate(
            [jnp.ones(y.shape[:-1] + (1,), y.dtype), sf[..., :-1]], -1)
        z = y_crop / sf
        return jnp.log(z) - jnp.log1p(-z) + \
            jnp.log(offset.astype(y.dtype))

    def _forward_log_det_jacobian(self, x):
        offset = x.shape[-1] + 1 - jnp.arange(1, x.shape[-1] + 1)
        t = x - jnp.log(offset.astype(x.dtype))
        z = jax.nn.sigmoid(t)
        zc = jnp.cumprod(1 - z, axis=-1)
        sf_prev = jnp.concatenate(
            [jnp.ones(x.shape[:-1] + (1,), x.dtype), zc[..., :-1]], -1)
        return jnp.sum(jnp.log(z) + jnp.log1p(-z) + jnp.log(sf_prev), -1)

    def forward_shape(self, shape):
        return tuple(shape[:-1]) + (shape[-1] + 1,)

    def inverse_shape(self, shape):
        return tuple(shape[:-1]) + (shape[-1] - 1,)


class StackTransform(Transform):
    """Apply a list of transforms to slices along an axis."""

    def __init__(self, transforms, axis=0):
        self.transforms = list(transforms)
        self.axis = axis

    def _apply(self, v, meth):
        # slice -> per-transform method -> stack, all on the tape so grads
        # flow through each sub-transform's parameters
        from paddle_tpu import tensor as T
        if not isinstance(v, Tensor):
            v = Tensor(jnp.asarray(v))
        arrs = T.split(v, len(self.transforms), self.axis)
        outs = []
        for t, a in zip(self.transforms, arrs):
            r = getattr(t, meth)(T.squeeze(a, self.axis))
            outs.append(r if isinstance(r, Tensor) else Tensor(jnp.asarray(r)))
        return T.stack(outs, self.axis)

    def forward(self, x):
        return self._apply(x, "forward")

    def inverse(self, y):
        return self._apply(y, "inverse")

    def forward_log_det_jacobian(self, x):
        return self._apply(x, "forward_log_det_jacobian")

    def inverse_log_det_jacobian(self, y):
        return self._apply(y, "inverse_log_det_jacobian")


class IndependentTransform(Transform):
    """Reinterpret batch dims of a base transform as event dims."""

    def __init__(self, base, reinterpreted_batch_rank):
        self.base = base
        self.reinterpreted_batch_rank = int(reinterpreted_batch_rank)
        self._domain_event_dim = (base._domain_event_dim
                                  + self.reinterpreted_batch_rank)
        self._codomain_event_dim = (base._codomain_event_dim
                                    + self.reinterpreted_batch_rank)

    def forward(self, x):
        return self.base.forward(x)

    def inverse(self, y):
        return self.base.inverse(y)

    def _sum_rightmost(self, ldj):
        if not isinstance(ldj, Tensor):
            ldj = Tensor(jnp.asarray(ldj))
        n = self.reinterpreted_batch_rank
        if n == 0 or ldj.ndim == 0:
            return ldj
        return U.op("independent_transform_sum", lambda a: jnp.sum(
            a, axis=tuple(range(a.ndim - n, a.ndim))), ldj)

    def forward_log_det_jacobian(self, x):
        return self._sum_rightmost(self.base.forward_log_det_jacobian(x))

    def inverse_log_det_jacobian(self, y):
        return self._sum_rightmost(self.base.inverse_log_det_jacobian(y))


class ChainTransform(Transform):
    def __init__(self, transforms):
        self.transforms = list(transforms)

    @property
    def _domain_event_dim(self):
        return max((t._domain_event_dim for t in self.transforms), default=0)

    @property
    def _codomain_event_dim(self):
        return max((t._codomain_event_dim for t in self.transforms),
                   default=0)

    def forward(self, x):
        for t in self.transforms:
            x = t.forward(x)
        return x

    def inverse(self, y):
        for t in reversed(self.transforms):
            y = t.inverse(y)
        return y

    def forward_log_det_jacobian(self, x):
        from paddle_tpu import tensor as T
        total = None
        for t in self.transforms:
            ldj = t.forward_log_det_jacobian(x)
            total = ldj if total is None else T.add(total, ldj)
            x = t.forward(x)
        return total

    def inverse_log_det_jacobian(self, y):
        from paddle_tpu import tensor as T
        total = None
        for t in reversed(self.transforms):
            ldj = t.inverse_log_det_jacobian(y)
            total = ldj if total is None else T.add(total, ldj)
            y = t.inverse(y)
        return total

    def forward_shape(self, shape):
        for t in self.transforms:
            shape = t.forward_shape(shape)
        return shape

    def inverse_shape(self, shape):
        for t in reversed(self.transforms):
            shape = t.inverse_shape(shape)
        return shape
