"""Distribution base classes.

Reference: python/paddle/distribution/distribution.py (class Distribution)
and exponential_family.py (ExponentialFamily).
"""
from __future__ import annotations

from paddle_tpu.core.tensor import Tensor
from . import _util as U


class Distribution:
    """Base class of probability distributions.

    Mirrors the reference API surface: batch_shape/event_shape, sample/
    rsample, prob/log_prob, cdf/icdf where defined, entropy,
    kl_divergence(other).
    """

    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(
            batch_shape if not isinstance(batch_shape, int) else (batch_shape,))
        self._event_shape = tuple(
            event_shape if not isinstance(event_shape, int) else (event_shape,))

    @property
    def batch_shape(self):
        return self._batch_shape

    @property
    def event_shape(self):
        return self._event_shape

    @property
    def mean(self):
        raise NotImplementedError

    @property
    def variance(self):
        raise NotImplementedError

    @property
    def stddev(self):
        from paddle_tpu import tensor as T
        return T.sqrt(self.variance)

    def sample(self, shape=()):
        """Draw (non-reparameterized) samples; gradients do not flow."""
        out = self.rsample(shape)
        if isinstance(out, Tensor):
            out = Tensor(out._value, stop_gradient=True)
        return out

    def rsample(self, shape=()):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        from paddle_tpu import tensor as T
        return T.exp(self.log_prob(value))

    def entropy(self):
        raise NotImplementedError

    def cdf(self, value):
        raise NotImplementedError

    def icdf(self, value):
        raise NotImplementedError

    def kl_divergence(self, other):
        from .kl import kl_divergence
        return kl_divergence(self, other)

    def _extend_shape(self, sample_shape):
        return U.sample_shape(sample_shape, self._batch_shape,
                              self._event_shape)

    def __repr__(self):
        return f"{type(self).__name__}(batch_shape={self._batch_shape}, " \
               f"event_shape={self._event_shape})"


class ExponentialFamily(Distribution):
    """Marker base for exponential-family distributions (API parity with
    the reference's exponential_family.py). The reference derives entropy
    generically from the log-normalizer via the Bregman identity with
    autodiff; here every subclass ships a closed-form entropy instead —
    same results, one less autodiff pass."""

