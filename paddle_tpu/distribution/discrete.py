"""Discrete distributions.

Reference: python/paddle/distribution/{bernoulli,binomial,categorical,
geometric,multinomial,poisson}.py. Conventions follow the reference:
Geometric counts failures before first success (pmf p(1-p)^k, k>=0,
mean 1/p - 1 — geometric.py:111,152); Categorical normalizes logits by
softmax and supports unnormalized inputs.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax.scipy import special as jsp

from paddle_tpu.core.tensor import Tensor
from . import _util as U
from .distribution import Distribution, ExponentialFamily


class Bernoulli(ExponentialFamily):
    """Bernoulli(probs). Reference: distribution/bernoulli.py."""

    def __init__(self, probs, name=None):
        self.probs = probs
        super().__init__(U.param_shape(probs))

    @property
    def logits(self):
        return U.op("bernoulli_logits",
                    lambda p: jnp.log(p) - jnp.log1p(-p), self.probs)

    @property
    def mean(self):
        return U.op("bernoulli_mean", lambda p: p * 1.0, self.probs)

    @property
    def variance(self):
        return U.op("bernoulli_var", lambda p: p * (1 - p), self.probs)

    def sample(self, shape=()):
        s = jax.random.bernoulli(
            U.key(), jnp.broadcast_to(U.arr(self.probs),
                                      self._extend_shape(shape)))
        return Tensor(s.astype(U.arr(self.probs).dtype))

    def rsample(self, shape=(), temperature=1.0):
        """Gumbel-softmax style relaxed sample (reference bernoulli.py
        rsample uses the same logistic relaxation)."""
        u = jax.random.uniform(U.key(), self._extend_shape(shape),
                               U.arr(self.probs).dtype, 1e-6, 1 - 1e-6)

        def f(p, u):
            logits = jnp.log(p) - jnp.log1p(-p)
            noise = jnp.log(u) - jnp.log1p(-u)
            return jax.nn.sigmoid((logits + noise) / temperature)
        return U.op("bernoulli_rsample", f, self.probs, u)

    def log_prob(self, value):
        return U.op("bernoulli_log_prob",
                    lambda v, p: jsp.xlogy(v, p) + jsp.xlog1py(1 - v, -p),
                    U.value_arr(value), self.probs)

    def entropy(self):
        return U.op(
            "bernoulli_entropy",
            lambda p: -(jsp.xlogy(p, p) + jsp.xlog1py(1 - p, -p)),
            self.probs)

    def cdf(self, value):
        def f(v, p):
            c = jnp.where(v < 0, 0.0, jnp.where(v < 1, 1 - p, 1.0))
            return c
        return U.op("bernoulli_cdf", f, U.value_arr(value), self.probs)


class Categorical(Distribution):
    """Categorical(logits): unnormalized log-probabilities over the last
    axis (softmax-normalized). Reference: distribution/categorical.py."""

    def __init__(self, logits, name=None):
        self.logits = logits
        shp = tuple(jnp.shape(U.arr(logits)))
        super().__init__(shp[:-1])
        self._num_categories = shp[-1]

    @property
    def probs(self):
        return U.op("categorical_probs",
                    lambda lg: jax.nn.softmax(lg, axis=-1), self.logits)

    def sample(self, shape=()):
        shp = U.sample_shape(shape, self._batch_shape)
        idx = jax.random.categorical(
            U.key(), jax.nn.log_softmax(U.arr(self.logits), axis=-1),
            shape=shp)
        return Tensor(idx.astype(jnp.int64 if jax.config.read("jax_enable_x64")
                                 else jnp.int32), stop_gradient=True)

    def log_prob(self, value):
        v = value._value if isinstance(value, Tensor) else jnp.asarray(value)
        v = v.astype(jnp.int32)

        def f(lg):
            logp = jax.nn.log_softmax(lg, axis=-1)
            shp = jnp.broadcast_shapes(jnp.shape(v), jnp.shape(lg)[:-1])
            vb = jnp.broadcast_to(v, shp)
            lb = jnp.broadcast_to(logp, shp + jnp.shape(lg)[-1:])
            return jnp.take_along_axis(lb, vb[..., None], axis=-1)[..., 0]
        return U.op("categorical_log_prob", f, self.logits)

    def probs_of(self, value):
        from paddle_tpu import tensor as T
        return T.exp(self.log_prob(value))

    def entropy(self):
        def f(lg):
            logp = jax.nn.log_softmax(lg, axis=-1)
            return -jnp.sum(jnp.exp(logp) * logp, axis=-1)
        return U.op("categorical_entropy", f, self.logits)


class Geometric(Distribution):
    """Geometric(probs): failures before first success, pmf p(1-p)^k.
    Reference: distribution/geometric.py:111,152,250."""

    def __init__(self, probs):
        self.probs = probs
        super().__init__(U.param_shape(probs))

    @property
    def mean(self):
        return U.op("geometric_mean", lambda p: 1.0 / p - 1.0, self.probs)

    @property
    def variance(self):
        return U.op("geometric_var",
                    lambda p: (1.0 / p - 1.0) / p, self.probs)

    def sample(self, shape=()):
        return Tensor(self.rsample(shape)._value, stop_gradient=True)

    def rsample(self, shape=()):
        u = jax.random.uniform(U.key(), self._extend_shape(shape),
                               U.arr(self.probs).dtype, 1e-7, 1 - 1e-7)
        return U.op("geometric_rsample",
                    lambda p, u: jnp.floor(jnp.log(u) / jnp.log1p(-p)),
                    self.probs, u)

    def pmf(self, k):
        from paddle_tpu import tensor as T
        return T.exp(self.log_pmf(k))

    def log_pmf(self, k):
        return self.log_prob(k)

    def log_prob(self, value):
        return U.op("geometric_log_prob",
                    lambda v, p: jnp.log(p) + jsp.xlog1py(v, -p),
                    U.value_arr(value), self.probs)

    def entropy(self):
        return U.op(
            "geometric_entropy",
            lambda p: -(jsp.xlogy(p, p) + jsp.xlog1py(1 - p, -p)) / p,
            self.probs)

    def cdf(self, value):
        return U.op("geometric_cdf",
                    lambda v, p: 1 - jnp.power(1 - p, v + 1),
                    U.value_arr(value), self.probs)


class Binomial(Distribution):
    """Binomial(total_count, probs). Reference: distribution/binomial.py."""

    def __init__(self, total_count, probs):
        self.total_count, self.probs = total_count, probs
        super().__init__(U.param_shape(total_count, probs))

    @property
    def mean(self):
        return U.op("binomial_mean", lambda n, p: n * p,
                    self.total_count, self.probs)

    @property
    def variance(self):
        return U.op("binomial_var", lambda n, p: n * p * (1 - p),
                    self.total_count, self.probs)

    def sample(self, shape=()):
        shp = self._extend_shape(shape)
        n = jnp.broadcast_to(U.arr(self.total_count), shp)
        p = jnp.broadcast_to(U.arr(self.probs), shp)
        s = jax.random.binomial(U.key(), n, p)
        return Tensor(s, stop_gradient=True)

    def log_prob(self, value):
        def f(v, n, p):
            logc = (jsp.gammaln(n + 1) - jsp.gammaln(v + 1)
                    - jsp.gammaln(n - v + 1))
            return logc + jsp.xlogy(v, p) + jsp.xlog1py(n - v, -p)
        return U.op("binomial_log_prob", f, U.value_arr(value),
                    self.total_count, self.probs)

    def entropy(self):
        """Exact entropy by summing the pmf over the support (static bound:
        max total_count)."""
        n_arr = U.arr(self.total_count)
        if isinstance(n_arr, jax.core.Tracer):
            kmax = 512  # static window under jit; exact for n < 512
        else:
            kmax = int(jnp.max(n_arr)) + 1

        def f(n, p):
            ks = jnp.arange(kmax, dtype=p.dtype if hasattr(p, "dtype")
                            else jnp.float32)
            shp = jnp.broadcast_shapes(jnp.shape(n), jnp.shape(p))
            nb = jnp.broadcast_to(n, shp)[..., None]
            pb = jnp.broadcast_to(p, shp)[..., None]
            logc = (jsp.gammaln(nb + 1) - jsp.gammaln(ks + 1)
                    - jsp.gammaln(nb - ks + 1))
            logpmf = logc + jsp.xlogy(ks, pb) + jsp.xlog1py(nb - ks, -pb)
            valid = ks <= nb
            pmf = jnp.where(valid, jnp.exp(logpmf), 0.0)
            return -jnp.sum(pmf * jnp.where(valid, logpmf, 0.0), axis=-1)
        return U.op(f"binomial_entropy_{kmax}", f,
                    self.total_count, self.probs)


class Multinomial(Distribution):
    """Multinomial(total_count, probs). Reference: multinomial.py."""

    def __init__(self, total_count, probs):
        self.total_count, self.probs = total_count, probs
        shp = tuple(jnp.shape(U.arr(probs)))
        super().__init__(shp[:-1], shp[-1:])

    @property
    def mean(self):
        return U.op("multinomial_mean",
                    lambda n, p: n * (p / jnp.sum(p, -1, keepdims=True)),
                    self.total_count, self.probs)

    @property
    def variance(self):
        def f(n, p):
            p = p / jnp.sum(p, -1, keepdims=True)
            return n * p * (1 - p)
        return U.op("multinomial_var", f, self.total_count, self.probs)

    def sample(self, shape=()):
        n_arr = U.arr(self.total_count)
        if n_arr.ndim != 0:
            raise ValueError(
                "Multinomial.sample requires a scalar total_count "
                f"(got shape {tuple(n_arr.shape)}); log_prob/mean/variance "
                "do support batched counts.")
        n = int(n_arr)
        p = U.arr(self.probs)
        shp = U.sample_shape(shape, self._batch_shape)
        logits = jnp.log(p / jnp.sum(p, -1, keepdims=True))
        idx = jax.random.categorical(U.key(), logits,
                                     shape=(n,) + shp)
        counts = jax.nn.one_hot(idx, p.shape[-1], dtype=p.dtype).sum(0)
        return Tensor(counts, stop_gradient=True)

    def log_prob(self, value):
        def f(v, n, p):
            p = p / jnp.sum(p, -1, keepdims=True)
            return (jsp.gammaln(n + 1)
                    - jnp.sum(jsp.gammaln(v + 1), axis=-1)
                    + jnp.sum(jsp.xlogy(v, p), axis=-1))
        return U.op("multinomial_log_prob", f, U.value_arr(value),
                    self.total_count, self.probs)

    def entropy(self):
        """Monte-Carlo entropy estimate (no closed form; the reference
        evaluates the same way via sampled log_prob)."""
        samples = self.sample((128,))
        lp = self.log_prob(samples)
        from paddle_tpu import tensor as T
        return T.mean(lp, axis=0) * (-1.0)


class Poisson(ExponentialFamily):
    """Poisson(rate). Reference: distribution/poisson.py."""

    _ENTROPY_TERMS = 512

    def __init__(self, rate):
        self.rate = rate
        super().__init__(U.param_shape(rate))

    @property
    def mean(self):
        return U.op("poisson_mean", lambda r: r * 1.0, self.rate)

    @property
    def variance(self):
        return U.op("poisson_var", lambda r: r * 1.0, self.rate)

    def sample(self, shape=()):
        s = jax.random.poisson(
            U.key(), jnp.broadcast_to(U.arr(self.rate),
                                      self._extend_shape(shape)))
        return Tensor(s.astype(U.arr(self.rate).dtype), stop_gradient=True)

    def log_prob(self, value):
        return U.op(
            "poisson_log_prob",
            lambda v, r: jsp.xlogy(v, r) - r - jsp.gammaln(v + 1),
            U.value_arr(value), self.rate)

    def entropy(self):
        """Series entropy -sum pmf*logpmf over a window centred on each
        rate (pmf mass lies within ~10 sigma of the rate, so a shifted
        window of ~24*sqrt(rate_max) terms is exact to float precision for
        any rate; a static 0-based window would silently lose the mass for
        rate >~ window)."""
        ra = U.arr(self.rate)
        if isinstance(ra, jax.core.Tracer):
            # static width under jit; the rate-centred shift below is
            # traceable so large rates stay accurate up to ~(width/10)^2
            width = self._ENTROPY_TERMS
        else:
            rmax = float(jnp.max(ra)) if ra.size else 0.0
            width = int(min(8192, max(self._ENTROPY_TERMS,
                                      24 * rmax ** 0.5 + 64)))

        def f(r):
            rb = jnp.asarray(r)[..., None]
            kstart = jnp.floor(jnp.maximum(rb - width / 2, 0.0))
            ks = kstart + jnp.arange(width, dtype=jnp.float32)
            logpmf = jsp.xlogy(ks, rb) - rb - jsp.gammaln(ks + 1)
            ent = -jnp.sum(jnp.exp(logpmf) * logpmf, axis=-1)
            return ent.reshape(jnp.shape(r))
        return U.op("poisson_entropy", f, self.rate)
