"""TransformedDistribution and Independent.

Reference: python/paddle/distribution/{transformed_distribution,
independent}.py.
"""
from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.core.tensor import Tensor
from . import _util as U
from .distribution import Distribution
from .transform import ChainTransform, Transform


class TransformedDistribution(Distribution):
    """Distribution of T(X) for X ~ base and T a (chain of) transform(s)."""

    def __init__(self, base, transforms):
        if isinstance(transforms, Transform):
            transforms = [transforms]
        self.base = base
        self.transforms = list(transforms)
        chain = ChainTransform(self.transforms)
        shape = tuple(base.batch_shape) + tuple(base.event_shape)
        out_shape = chain.forward_shape(shape)
        event_dim = max(chain._codomain_event_dim, len(base.event_shape))
        cut = len(out_shape) - event_dim
        super().__init__(out_shape[:cut], out_shape[cut:])

    def sample(self, shape=()):
        x = self.base.sample(shape)
        for t in self.transforms:
            x = t.forward(x)
        if isinstance(x, Tensor):
            x = Tensor(x._value, stop_gradient=True)
        return x

    def rsample(self, shape=()):
        x = self.base.rsample(shape)
        for t in self.transforms:
            x = t.forward(x)
        return x

    def log_prob(self, value):
        from paddle_tpu import tensor as T
        event_dim = len(self.event_shape)
        lp = None
        y = value
        for t in reversed(self.transforms):
            x = t.inverse(y)
            ildj = t.inverse_log_det_jacobian(y)
            ndiff = event_dim - t._codomain_event_dim
            term = ildj if isinstance(ildj, Tensor) else Tensor(
                jnp.asarray(ildj))
            if ndiff > 0 and term.ndim >= ndiff:
                # tape-preserving trailing-axis sum: grads must flow to
                # transform parameters through the Jacobian term
                term = U.op("tdist_ildj_sum", lambda a, nd=ndiff: jnp.sum(
                    a, axis=tuple(range(a.ndim - nd, a.ndim))), term)
            lp = term if lp is None else T.add(lp, term)
            event_dim = t._domain_event_dim + max(
                event_dim - t._codomain_event_dim, 0)
            y = x
        base_lp = self.base.log_prob(y)
        ndiff = event_dim - len(self.base.event_shape)
        if ndiff > 0:
            base_lp = U.op("tdist_base_sum", lambda a, nd=ndiff: jnp.sum(
                a, axis=tuple(range(a.ndim - nd, a.ndim))), base_lp)
        return T.add(base_lp, lp) if lp is not None else base_lp


class Independent(Distribution):
    """Reinterpret `reinterpreted_batch_rank` rightmost batch dims as
    event dims (sums log_prob over them)."""

    def __init__(self, base, reinterpreted_batch_rank):
        self.base = base
        self.reinterpreted_batch_rank = int(reinterpreted_batch_rank)
        if self.reinterpreted_batch_rank > len(base.batch_shape):
            raise ValueError("reinterpreted_batch_rank too large")
        b = tuple(base.batch_shape)
        cut = len(b) - self.reinterpreted_batch_rank
        super().__init__(b[:cut], b[cut:] + tuple(base.event_shape))

    @property
    def mean(self):
        return self.base.mean

    @property
    def variance(self):
        return self.base.variance

    def sample(self, shape=()):
        return self.base.sample(shape)

    def rsample(self, shape=()):
        return self.base.rsample(shape)

    def log_prob(self, value):
        lp = self.base.log_prob(value)
        n = self.reinterpreted_batch_rank
        return U.op("independent_sum", lambda a: jnp.sum(
            a, axis=tuple(range(a.ndim - n, a.ndim))), lp)

    def entropy(self):
        ent = self.base.entropy()
        n = self.reinterpreted_batch_rank
        return U.op("independent_sum", lambda a: jnp.sum(
            a, axis=tuple(range(a.ndim - n, a.ndim))), ent)
