"""Continuous distributions.

Reference: python/paddle/distribution/{normal,uniform,beta,cauchy,
continuous_bernoulli,exponential,gamma,gumbel,laplace,lognormal}.py and
chi2/student_t. Math rebuilt as pure jax functions over lax/jnp; every
differentiable method goes through the eager dispatcher (see _util.op).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax.scipy import special as jsp

from paddle_tpu.core.tensor import Tensor
from . import _util as U
from .distribution import Distribution, ExponentialFamily

_HALF_LOG_2PI = 0.5 * math.log(2.0 * math.pi)


class Normal(ExponentialFamily):
    """Normal(loc, scale). Reference: distribution/normal.py."""

    def __init__(self, loc, scale, name=None):
        self.loc, self.scale = loc, scale
        super().__init__(U.param_shape(loc, scale))

    @property
    def mean(self):
        return U.op("normal_mean", lambda l, s: jnp.broadcast_to(
            l, jnp.broadcast_shapes(jnp.shape(l), jnp.shape(s))),
            self.loc, self.scale)

    @property
    def variance(self):
        return U.op("normal_var", lambda l, s: jnp.broadcast_to(
            s * s, jnp.broadcast_shapes(jnp.shape(l), jnp.shape(s))),
            self.loc, self.scale)

    def rsample(self, shape=()):
        eps = jax.random.normal(U.key(), self._extend_shape(shape),
                                U.arr(self.loc).dtype)
        return U.op("normal_rsample", lambda l, s, e: l + s * e,
                    self.loc, self.scale, eps)

    def log_prob(self, value):
        return U.op(
            "normal_log_prob",
            lambda v, l, s: -((v - l) ** 2) / (2 * s * s) - jnp.log(s)
            - _HALF_LOG_2PI,
            U.value_arr(value), self.loc, self.scale)

    def entropy(self):
        return U.op(
            "normal_entropy",
            lambda l, s: jnp.broadcast_to(0.5 + _HALF_LOG_2PI + jnp.log(s),
                                          jnp.broadcast_shapes(
                                              jnp.shape(l), jnp.shape(s))),
            self.loc, self.scale)

    def cdf(self, value):
        return U.op("normal_cdf",
                    lambda v, l, s: jsp.ndtr((v - l) / s),
                    U.value_arr(value), self.loc, self.scale)

    def icdf(self, value):
        return U.op("normal_icdf",
                    lambda v, l, s: l + s * jsp.ndtri(v),
                    U.value_arr(value), self.loc, self.scale)

    def probs(self, value):
        return self.prob(value)


class Uniform(Distribution):
    """Uniform(low, high). Reference: distribution/uniform.py."""

    def __init__(self, low, high, name=None):
        self.low, self.high = low, high
        super().__init__(U.param_shape(low, high))

    @property
    def mean(self):
        return U.op("uniform_mean", lambda a, b: (a + b) / 2,
                    self.low, self.high)

    @property
    def variance(self):
        return U.op("uniform_var", lambda a, b: (b - a) ** 2 / 12,
                    self.low, self.high)

    def rsample(self, shape=()):
        u = jax.random.uniform(U.key(), self._extend_shape(shape),
                               U.arr(self.low).dtype)
        return U.op("uniform_rsample", lambda a, b, u: a + (b - a) * u,
                    self.low, self.high, u)

    def log_prob(self, value):
        def f(v, a, b):
            inside = (v >= a) & (v < b)
            return jnp.where(inside, -jnp.log(b - a), -jnp.inf)
        return U.op("uniform_log_prob", f, U.value_arr(value),
                    self.low, self.high)

    def entropy(self):
        return U.op("uniform_entropy", lambda a, b: jnp.log(b - a),
                    self.low, self.high)

    def cdf(self, value):
        return U.op("uniform_cdf",
                    lambda v, a, b: jnp.clip((v - a) / (b - a), 0.0, 1.0),
                    U.value_arr(value), self.low, self.high)


class Beta(ExponentialFamily):
    """Beta(alpha, beta). Reference: distribution/beta.py."""

    def __init__(self, alpha, beta):
        self.alpha, self.beta = alpha, beta
        super().__init__(U.param_shape(alpha, beta))

    @property
    def mean(self):
        return U.op("beta_mean", lambda a, b: a / (a + b),
                    self.alpha, self.beta)

    @property
    def variance(self):
        return U.op("beta_var",
                    lambda a, b: a * b / ((a + b) ** 2 * (a + b + 1)),
                    self.alpha, self.beta)

    def rsample(self, shape=()):
        shp = self._extend_shape(shape)
        a, b = jnp.broadcast_to(U.arr(self.alpha), shp), \
            jnp.broadcast_to(U.arr(self.beta), shp)
        k1, k2 = jax.random.split(U.key())
        ga = jax.random.gamma(k1, a)
        gb = jax.random.gamma(k2, b)
        return Tensor(ga / (ga + gb))

    def log_prob(self, value):
        return U.op(
            "beta_log_prob",
            lambda v, a, b: jsp.xlogy(a - 1, v) + jsp.xlog1py(b - 1, -v)
            - jsp.betaln(a, b),
            U.value_arr(value), self.alpha, self.beta)

    def entropy(self):
        def f(a, b):
            tot = a + b
            return (jsp.betaln(a, b) - (a - 1) * jsp.digamma(a)
                    - (b - 1) * jsp.digamma(b)
                    + (tot - 2) * jsp.digamma(tot))
        return U.op("beta_entropy", f, self.alpha, self.beta)


class Cauchy(Distribution):
    """Cauchy(loc, scale). Reference: distribution/cauchy.py."""

    def __init__(self, loc, scale, name=None):
        self.loc, self.scale = loc, scale
        super().__init__(U.param_shape(loc, scale))

    @property
    def mean(self):
        raise ValueError("Cauchy distribution has no mean")

    @property
    def variance(self):
        raise ValueError("Cauchy distribution has no variance")

    @property
    def stddev(self):
        raise ValueError("Cauchy distribution has no stddev")

    def rsample(self, shape=()):
        u = jax.random.uniform(U.key(), self._extend_shape(shape),
                               U.arr(self.loc).dtype, 1e-7, 1 - 1e-7)
        return U.op("cauchy_rsample",
                    lambda l, s, u: l + s * jnp.tan(math.pi * (u - 0.5)),
                    self.loc, self.scale, u)

    def log_prob(self, value):
        return U.op(
            "cauchy_log_prob",
            lambda v, l, s: -math.log(math.pi) - jnp.log(s)
            - jnp.log1p(((v - l) / s) ** 2),
            U.value_arr(value), self.loc, self.scale)

    def entropy(self):
        return U.op("cauchy_entropy",
                    lambda l, s: jnp.broadcast_to(
                        jnp.log(4 * math.pi * s),
                        jnp.broadcast_shapes(jnp.shape(l), jnp.shape(s))),
                    self.loc, self.scale)

    def cdf(self, value):
        return U.op(
            "cauchy_cdf",
            lambda v, l, s: jnp.arctan((v - l) / s) / math.pi + 0.5,
            U.value_arr(value), self.loc, self.scale)


class Exponential(ExponentialFamily):
    """Exponential(rate). Reference: distribution/exponential.py."""

    def __init__(self, rate):
        self.rate = rate
        super().__init__(U.param_shape(rate))

    @property
    def mean(self):
        return U.op("exp_mean", lambda r: 1.0 / r, self.rate)

    @property
    def variance(self):
        return U.op("exp_var", lambda r: 1.0 / (r * r), self.rate)

    def rsample(self, shape=()):
        e = jax.random.exponential(U.key(), self._extend_shape(shape),
                                   U.arr(self.rate).dtype)
        return U.op("exp_rsample", lambda r, e: e / r, self.rate, e)

    def log_prob(self, value):
        return U.op("exp_log_prob",
                    lambda v, r: jnp.where(v >= 0, jnp.log(r) - r * v,
                                           -jnp.inf),
                    U.value_arr(value), self.rate)

    def entropy(self):
        return U.op("exp_entropy", lambda r: 1.0 - jnp.log(r), self.rate)

    def cdf(self, value):
        return U.op("exp_cdf",
                    lambda v, r: jnp.clip(1 - jnp.exp(-r * v), 0.0),
                    U.value_arr(value), self.rate)


class Gamma(ExponentialFamily):
    """Gamma(concentration, rate). Reference: distribution/gamma.py."""

    def __init__(self, concentration, rate):
        self.concentration, self.rate = concentration, rate
        super().__init__(U.param_shape(concentration, rate))

    @property
    def mean(self):
        return U.op("gamma_mean", lambda a, r: a / r,
                    self.concentration, self.rate)

    @property
    def variance(self):
        return U.op("gamma_var", lambda a, r: a / (r * r),
                    self.concentration, self.rate)

    def rsample(self, shape=()):
        shp = self._extend_shape(shape)
        k = U.key()
        # jax.random.gamma is differentiable in its shape parameter
        # (implicit reparameterization), matching the reference's rsample.
        return U.op(
            "gamma_rsample",
            lambda a, r: jax.random.gamma(
                k, jnp.broadcast_to(a, shp)) / r,
            self.concentration, self.rate)

    def log_prob(self, value):
        return U.op(
            "gamma_log_prob",
            lambda v, a, r: jsp.xlogy(a, r) + jsp.xlogy(a - 1, v) - r * v
            - jsp.gammaln(a),
            U.value_arr(value), self.concentration, self.rate)

    def entropy(self):
        return U.op(
            "gamma_entropy",
            lambda a, r: a - jnp.log(r) + jsp.gammaln(a)
            + (1 - a) * jsp.digamma(a),
            self.concentration, self.rate)


class Chi2(Gamma):
    """Chi2(df) = Gamma(df/2, 1/2). Reference: distribution/chi2.py."""

    def __init__(self, df):
        self.df = df
        super().__init__(
            U.op("chi2_conc", lambda d: d / 2.0, df),
            0.5)


class Gumbel(Distribution):
    """Gumbel(loc, scale). Reference: distribution/gumbel.py."""

    def __init__(self, loc, scale):
        self.loc, self.scale = loc, scale
        super().__init__(U.param_shape(loc, scale))

    @property
    def mean(self):
        return U.op("gumbel_mean",
                    lambda l, s: l + s * U.EULER_GAMMA, self.loc, self.scale)

    @property
    def variance(self):
        return U.op("gumbel_var",
                    lambda l, s: jnp.broadcast_to(
                        (math.pi ** 2 / 6) * s * s,
                        jnp.broadcast_shapes(jnp.shape(l), jnp.shape(s))),
                    self.loc, self.scale)

    def rsample(self, shape=()):
        u = jax.random.uniform(U.key(), self._extend_shape(shape),
                               U.arr(self.loc).dtype, 1e-7, 1 - 1e-7)
        return U.op("gumbel_rsample",
                    lambda l, s, u: l - s * jnp.log(-jnp.log(u)),
                    self.loc, self.scale, u)

    def log_prob(self, value):
        def f(v, l, s):
            z = (v - l) / s
            return -z - jnp.exp(-z) - jnp.log(s)
        return U.op("gumbel_log_prob", f, U.value_arr(value),
                    self.loc, self.scale)

    def entropy(self):
        return U.op("gumbel_entropy",
                    lambda l, s: jnp.broadcast_to(
                        jnp.log(s) + 1 + U.EULER_GAMMA,
                        jnp.broadcast_shapes(jnp.shape(l), jnp.shape(s))),
                    self.loc, self.scale)

    def cdf(self, value):
        return U.op("gumbel_cdf",
                    lambda v, l, s: jnp.exp(-jnp.exp(-(v - l) / s)),
                    U.value_arr(value), self.loc, self.scale)


class Laplace(Distribution):
    """Laplace(loc, scale). Reference: distribution/laplace.py."""

    def __init__(self, loc, scale):
        self.loc, self.scale = loc, scale
        super().__init__(U.param_shape(loc, scale))

    @property
    def mean(self):
        return U.op("laplace_mean", lambda l, s: jnp.broadcast_to(
            l, jnp.broadcast_shapes(jnp.shape(l), jnp.shape(s))),
            self.loc, self.scale)

    @property
    def variance(self):
        return U.op("laplace_var", lambda l, s: jnp.broadcast_to(
            2 * s * s, jnp.broadcast_shapes(jnp.shape(l), jnp.shape(s))),
            self.loc, self.scale)

    def rsample(self, shape=()):
        u = jax.random.uniform(U.key(), self._extend_shape(shape),
                               U.arr(self.loc).dtype, 1e-7, 1 - 1e-7) - 0.5
        return U.op(
            "laplace_rsample",
            lambda l, s, u: l - s * jnp.sign(u) * jnp.log1p(-2 * jnp.abs(u)),
            self.loc, self.scale, u)

    def log_prob(self, value):
        return U.op(
            "laplace_log_prob",
            lambda v, l, s: -jnp.abs(v - l) / s - jnp.log(2 * s),
            U.value_arr(value), self.loc, self.scale)

    def entropy(self):
        return U.op("laplace_entropy",
                    lambda l, s: jnp.broadcast_to(
                        1 + jnp.log(2 * s),
                        jnp.broadcast_shapes(jnp.shape(l), jnp.shape(s))),
                    self.loc, self.scale)

    def cdf(self, value):
        def f(v, l, s):
            z = (v - l) / s
            return 0.5 - 0.5 * jnp.sign(z) * jnp.expm1(-jnp.abs(z))
        return U.op("laplace_cdf", f, U.value_arr(value),
                    self.loc, self.scale)

    def icdf(self, value):
        def f(p, l, s):
            t = p - 0.5
            return l - s * jnp.sign(t) * jnp.log1p(-2 * jnp.abs(t))
        return U.op("laplace_icdf", f, U.value_arr(value),
                    self.loc, self.scale)


class LogNormal(Distribution):
    """LogNormal(loc, scale) = exp(Normal). Reference: lognormal.py."""

    def __init__(self, loc, scale):
        self.loc, self.scale = loc, scale
        self._base = Normal(loc, scale)
        super().__init__(U.param_shape(loc, scale))

    @property
    def mean(self):
        return U.op("lognormal_mean",
                    lambda l, s: jnp.exp(l + s * s / 2),
                    self.loc, self.scale)

    @property
    def variance(self):
        return U.op(
            "lognormal_var",
            lambda l, s: jnp.expm1(s * s) * jnp.exp(2 * l + s * s),
            self.loc, self.scale)

    def rsample(self, shape=()):
        from paddle_tpu import tensor as T
        return T.exp(self._base.rsample(shape))

    def log_prob(self, value):
        return U.op(
            "lognormal_log_prob",
            lambda v, l, s: -((jnp.log(v) - l) ** 2) / (2 * s * s)
            - jnp.log(s * v) - _HALF_LOG_2PI,
            U.value_arr(value), self.loc, self.scale)

    def entropy(self):
        return U.op("lognormal_entropy",
                    lambda l, s: 0.5 + _HALF_LOG_2PI + jnp.log(s) + l,
                    self.loc, self.scale)


class StudentT(Distribution):
    """StudentT(df, loc, scale). Reference: distribution/student_t.py."""

    def __init__(self, df, loc=0.0, scale=1.0):
        self.df, self.loc, self.scale = df, loc, scale
        super().__init__(U.param_shape(df, loc, scale))

    @property
    def mean(self):
        return U.op("studentt_mean",
                    lambda d, l, s: jnp.where(d > 1, l, jnp.nan),
                    self.df, self.loc, self.scale)

    @property
    def variance(self):
        def f(d, l, s):
            v = jnp.where(d > 2, s * s * d / (d - 2), jnp.inf)
            return jnp.where(d > 1, v, jnp.nan)
        return U.op("studentt_var", f, self.df, self.loc, self.scale)

    def rsample(self, shape=()):
        shp = self._extend_shape(shape)
        k = U.key()

        def f(d, l, s):
            t = jax.random.t(k, jnp.broadcast_to(d, shp))
            return l + s * t
        return U.op("studentt_rsample", f, self.df, self.loc, self.scale)

    def log_prob(self, value):
        def f(v, d, l, s):
            z = (v - l) / s
            return (jsp.gammaln((d + 1) / 2) - jsp.gammaln(d / 2)
                    - 0.5 * jnp.log(d * math.pi) - jnp.log(s)
                    - (d + 1) / 2 * jnp.log1p(z * z / d))
        return U.op("studentt_log_prob", f, U.value_arr(value),
                    self.df, self.loc, self.scale)

    def entropy(self):
        def f(d, l, s):
            ent = ((d + 1) / 2 * (jsp.digamma((d + 1) / 2)
                                  - jsp.digamma(d / 2))
                   + 0.5 * jnp.log(d) + jsp.betaln(d / 2, 0.5) + jnp.log(s))
            return jnp.broadcast_to(ent, jnp.broadcast_shapes(
                jnp.shape(d), jnp.shape(l), jnp.shape(s)))
        return U.op("studentt_entropy", f, self.df, self.loc, self.scale)


class ContinuousBernoulli(Distribution):
    """ContinuousBernoulli(probs). Reference: continuous_bernoulli.py."""

    def __init__(self, probs, lims=(0.499, 0.501)):
        self.probs = probs
        self._lims = lims
        super().__init__(U.param_shape(probs))

    def _cut(self, p):
        lo, hi = self._lims
        return jnp.where((p > lo) & (p < hi), lo, p)

    def _log_norm(self, p):
        # log C(p); C = 2 atanh(1-2p)/(1-2p) for p != 1/2, else 2
        pc = self._cut(p)
        x = 1 - 2 * pc
        out = jnp.log(2 * jnp.abs(jnp.arctanh(x)) / jnp.abs(x))
        taylor = math.log(2.0) + (4.0 / 3 + 104.0 / 45 * (p - 0.5) ** 2) \
            * (p - 0.5) ** 2
        lo, hi = self._lims
        return jnp.where((p > lo) & (p < hi), taylor, out)

    @property
    def mean(self):
        def f(p):
            pc = self._cut(p)
            m = pc / (2 * pc - 1) + 1 / (2 * jnp.arctanh(1 - 2 * pc))
            taylor = 0.5 + (p - 0.5) / 3 + 16.0 / 45 * (p - 0.5) ** 3
            lo, hi = self._lims
            return jnp.where((p > lo) & (p < hi), taylor, m)
        return U.op("cb_mean", f, self.probs)

    @property
    def variance(self):
        def f(p):
            pc = self._cut(p)
            at = jnp.arctanh(1 - 2 * pc)
            v = pc * (pc - 1) / (1 - 2 * pc) ** 2 + 1 / (2 * at) ** 2
            taylor = 1.0 / 12 - (p - 0.5) ** 2 / 15
            lo, hi = self._lims
            return jnp.where((p > lo) & (p < hi), taylor, v)
        return U.op("cb_var", f, self.probs)

    def rsample(self, shape=()):
        u = jax.random.uniform(U.key(), self._extend_shape(shape),
                               U.arr(self.probs).dtype, 1e-6, 1 - 1e-6)
        return U.op("cb_rsample", lambda p, u: self._icdf_arr(p, u),
                    self.probs, u)

    def _icdf_arr(self, p, u):
        pc = self._cut(p)
        icdf = (jnp.log1p(u * (2 * pc - 1) / (1 - pc))
                / (jnp.log(pc) - jnp.log1p(-pc)))
        lo, hi = self._lims
        return jnp.where((p > lo) & (p < hi), u, icdf)

    def icdf(self, value):
        return U.op("cb_icdf", lambda p, v: self._icdf_arr(p, v),
                    self.probs, U.value_arr(value))

    def cdf(self, value):
        def f(p, v):
            pc = self._cut(p)
            c = (pc ** v * (1 - pc) ** (1 - v) + pc - 1) / (2 * pc - 1)
            lo, hi = self._lims
            out = jnp.where((p > lo) & (p < hi), v, c)
            return jnp.clip(out, 0.0, 1.0)
        return U.op("cb_cdf", f, self.probs, U.value_arr(value))

    def log_prob(self, value):
        return U.op(
            "cb_log_prob",
            lambda p, v: jsp.xlogy(v, p) + jsp.xlog1py(1 - v, -p)
            + self._log_norm(p),
            self.probs, U.value_arr(value))

    def entropy(self):
        def f(p):
            pc = self._cut(p)
            at = jnp.arctanh(1 - 2 * pc)
            m = pc / (2 * pc - 1) + 1 / (2 * at)
            lo, hi = self._lims
            taylor_m = 0.5 + (p - 0.5) / 3 + 16.0 / 45 * (p - 0.5) ** 3
            m = jnp.where((p > lo) & (p < hi), taylor_m, m)
            return (- jsp.xlogy(m, p) - jsp.xlog1py(1 - m, -p)
                    - self._log_norm(p))
        return U.op("cb_entropy", f, self.probs)
