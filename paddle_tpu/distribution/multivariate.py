"""Multivariate distributions: Dirichlet, MultivariateNormal, LKJCholesky.

Reference: python/paddle/distribution/{dirichlet,multivariate_normal,
lkj_cholesky}.py.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax.scipy import special as jsp

from paddle_tpu.core.tensor import Tensor
from . import _util as U
from .distribution import Distribution, ExponentialFamily


class Dirichlet(ExponentialFamily):
    """Dirichlet(concentration). Reference: distribution/dirichlet.py."""

    def __init__(self, concentration):
        self.concentration = concentration
        shp = tuple(jnp.shape(U.arr(concentration)))
        super().__init__(shp[:-1], shp[-1:])

    @property
    def mean(self):
        return U.op("dirichlet_mean",
                    lambda c: c / jnp.sum(c, -1, keepdims=True),
                    self.concentration)

    @property
    def variance(self):
        def f(c):
            tot = jnp.sum(c, -1, keepdims=True)
            m = c / tot
            return m * (1 - m) / (tot + 1)
        return U.op("dirichlet_var", f, self.concentration)

    def rsample(self, shape=()):
        shp = U.sample_shape(shape, self._batch_shape, self._event_shape)
        k = U.key()
        return U.op(
            "dirichlet_rsample",
            lambda c: jax.random.dirichlet(
                k, jnp.broadcast_to(c, shp)), self.concentration)

    def log_prob(self, value):
        return U.op(
            "dirichlet_log_prob",
            lambda v, c: jnp.sum(jsp.xlogy(c - 1, v), -1)
            + jsp.gammaln(jnp.sum(c, -1)) - jnp.sum(jsp.gammaln(c), -1),
            U.value_arr(value), self.concentration)

    def entropy(self):
        def f(c):
            k = c.shape[-1]
            tot = jnp.sum(c, -1)
            lnB = jnp.sum(jsp.gammaln(c), -1) - jsp.gammaln(tot)
            return (lnB + (tot - k) * jsp.digamma(tot)
                    - jnp.sum((c - 1) * jsp.digamma(c), -1))
        return U.op("dirichlet_entropy", f, self.concentration)


class MultivariateNormal(Distribution):
    """MultivariateNormal(loc, covariance_matrix|precision_matrix|
    scale_tril). Reference: distribution/multivariate_normal.py."""

    def __init__(self, loc, covariance_matrix=None, precision_matrix=None,
                 scale_tril=None):
        given = sum(x is not None for x in
                    (covariance_matrix, precision_matrix, scale_tril))
        if given != 1:
            raise ValueError(
                "Exactly one of covariance_matrix, precision_matrix or "
                "scale_tril must be specified.")
        self.loc = loc
        if scale_tril is not None:
            self.scale_tril = scale_tril
        elif covariance_matrix is not None:
            self.covariance_matrix = covariance_matrix
            self.scale_tril = U.op(
                "mvn_chol", jnp.linalg.cholesky, covariance_matrix)
        else:
            self.precision_matrix = precision_matrix
            self.scale_tril = U.op(
                "mvn_prec_chol",
                lambda p: jnp.linalg.cholesky(jnp.linalg.inv(p)),
                precision_matrix)
        d = tuple(jnp.shape(U.arr(self.scale_tril)))[-1]
        batch = jnp.broadcast_shapes(
            tuple(jnp.shape(U.arr(loc)))[:-1],
            tuple(jnp.shape(U.arr(self.scale_tril)))[:-2])
        super().__init__(batch, (d,))

    @property
    def mean(self):
        return U.op("mvn_mean",
                    lambda l: jnp.broadcast_to(
                        l, self._batch_shape + self._event_shape), self.loc)

    @property
    def variance(self):
        return U.op(
            "mvn_var",
            lambda L: jnp.broadcast_to(
                jnp.sum(L * L, axis=-1),
                self._batch_shape + self._event_shape), self.scale_tril)

    def rsample(self, shape=()):
        shp = U.sample_shape(shape, self._batch_shape, self._event_shape)
        eps = jax.random.normal(U.key(), shp, U.arr(self.loc).dtype)
        return U.op(
            "mvn_rsample",
            lambda l, L, e: l + jnp.einsum("...ij,...j->...i", L, e),
            self.loc, self.scale_tril, eps)

    def log_prob(self, value):
        def f(v, l, L):
            diff = v - l
            # solve L y = diff (lower triangular)
            y = jax.scipy.linalg.solve_triangular(
                jnp.broadcast_to(
                    L, jnp.broadcast_shapes(
                        jnp.shape(L), jnp.shape(diff)[:-1] + jnp.shape(L)[-2:]
                    )), diff[..., None], lower=True)[..., 0]
            d = L.shape[-1]
            half_log_det = jnp.sum(
                jnp.log(jnp.diagonal(L, axis1=-2, axis2=-1)), -1)
            return (-0.5 * jnp.sum(y * y, -1) - half_log_det
                    - 0.5 * d * math.log(2 * math.pi))
        return U.op("mvn_log_prob", f, U.value_arr(value), self.loc,
                    self.scale_tril)

    def entropy(self):
        def f(L):
            d = L.shape[-1]
            half_log_det = jnp.sum(
                jnp.log(jnp.diagonal(L, axis1=-2, axis2=-1)), -1)
            ent = 0.5 * d * (1 + math.log(2 * math.pi)) + half_log_det
            return jnp.broadcast_to(ent, self._batch_shape)
        return U.op("mvn_entropy", f, self.scale_tril)


class LKJCholesky(Distribution):
    """LKJ prior over Cholesky factors of correlation matrices.
    Reference: distribution/lkj_cholesky.py (onion-method sampling)."""

    def __init__(self, dim, concentration=1.0,
                 sample_method="onion"):
        if dim < 2:
            raise ValueError("dim must be >= 2")
        self.dim = dim
        self.concentration = concentration
        self.sample_method = sample_method
        batch = tuple(jnp.shape(U.arr(concentration)))
        super().__init__(batch, (dim, dim))

    def sample(self, shape=()):
        """Onion-method sampler."""
        d = self.dim
        eta = U.arr(self.concentration)
        batch = U.sample_shape(shape, self._batch_shape)
        k1, k2 = jax.random.split(U.key())
        # beta_k = eta + (d - 2 - (k-1))/2 for row k = 1..d-1
        rows = []
        L00 = jnp.ones(batch)
        us = jax.random.normal(k1, batch + (d, d))
        for i in range(1, d):
            beta_a = jnp.broadcast_to(i / 2.0, batch)
            beta_b = eta + (d - 1 - i) / 2.0
            ka, kb, k2 = jax.random.split(k2, 3)
            g1 = jax.random.gamma(ka, jnp.broadcast_to(beta_a, batch))
            g2 = jax.random.gamma(kb, jnp.broadcast_to(beta_b, batch))
            y = g1 / (g1 + g2)           # Beta(i/2, eta + (d-1-i)/2)
            u = us[..., i, :i]
            norm = jnp.linalg.norm(u, axis=-1, keepdims=True)
            w = jnp.sqrt(y)[..., None] * u / jnp.where(norm == 0, 1.0, norm)
            rows.append((w, jnp.sqrt(jnp.clip(1 - y, 1e-12))))
        L = jnp.zeros(batch + (d, d))
        L = L.at[..., 0, 0].set(L00)
        for i, (w, diag) in enumerate(rows, start=1):
            L = L.at[..., i, :i].set(w)
            L = L.at[..., i, i].set(diag)
        return Tensor(L, stop_gradient=True)

    def log_prob(self, value):
        d = self.dim

        def f(L, eta):
            diag = jnp.diagonal(L, axis1=-2, axis2=-1)[..., 1:]
            # exponent of L_ii for i=1..d-1: 2(eta-1) + d - i - 1
            i = jnp.arange(1, d, dtype=diag.dtype)
            eta_b = eta[..., None] if jnp.ndim(eta) else eta
            exps = 2 * (eta_b - 1) + d - i - 1
            unnorm = jnp.sum(exps * jnp.log(diag), axis=-1)
            dm1 = d - 1
            alpha = eta + 0.5 * dm1
            lognorm = (0.5 * dm1 * math.log(math.pi)
                       + jsp.multigammaln(alpha - 0.5, dm1)
                       - dm1 * jsp.gammaln(alpha))
            return unnorm - lognorm
        return U.op(f"lkj_log_prob_{d}", f, U.value_arr(value),
                    self.concentration)
