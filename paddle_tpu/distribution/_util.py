"""Shared helpers for paddle_tpu.distribution.

TPU-native rebuild of the reference probability library
(reference: python/paddle/distribution/ — ~8k LoC over 25 files). Parameters
may be python scalars, numpy arrays, or paddle_tpu Tensors; distribution
math is written as pure jax functions registered once through the eager op
registry (paddle_tpu.core.dispatch.OpDef) so log_prob/entropy/rsample are
differentiable w.r.t. Tensor parameters on the eager tape and traceable
under jit — replacing the reference's per-method paddle-op compositions.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.dispatch import OpDef, dispatch
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.core.random import next_key

EULER_GAMMA = 0.57721566490153286060


def op(name, fn, *args):
    """Run pure jax fn through the eager dispatcher (autograd + AMP + jit
    compatible). A fresh OpDef per call: fns routinely close over
    sample-shape/key state, so caching by name would replay stale
    closures."""
    return dispatch(OpDef("distribution." + name, fn), args, {})


def arr(x, dtype=None):
    """Raw jnp array view of a parameter (loses autograd tracking; use for
    shape/static inspection, sampling noise, and non-differentiable paths)."""
    if isinstance(x, Tensor):
        a = x._value
    else:
        a = jnp.asarray(x)
    if jnp.issubdtype(a.dtype, jnp.integer) or a.dtype == jnp.bool_:
        a = a.astype(jnp.float32)
    if dtype is not None:
        a = a.astype(dtype)
    return a


def value_arr(x):
    """Array for an observed value (keeps Tensor for autograd dispatch)."""
    return x if isinstance(x, Tensor) else jnp.asarray(arr(x))


def broadcast_shapes(*shapes):
    return jnp.broadcast_shapes(*shapes)


def param_shape(*params):
    return jnp.broadcast_shapes(*[tuple(np.shape(arr(p))) for p in params])


def key():
    return next_key()


def sample_shape(shape, batch_shape, event_shape=()):
    if isinstance(shape, int):
        shape = (shape,)
    return tuple(shape) + tuple(batch_shape) + tuple(event_shape)
