"""paddle_tpu.distribution — probability distributions, transforms, KL.

TPU-native rebuild of the reference probability library (reference:
python/paddle/distribution/__init__.py — 25 distributions, the transform
family, and the KL registry). All math is pure-jax through the eager op
dispatcher: differentiable on the tape, traceable under jit.
"""
from .distribution import Distribution, ExponentialFamily
from .continuous import (Beta, Cauchy, Chi2, ContinuousBernoulli,
                         Exponential, Gamma, Gumbel, Laplace, LogNormal,
                         Normal, StudentT, Uniform)
from .discrete import (Bernoulli, Binomial, Categorical, Geometric,
                       Multinomial, Poisson)
from .multivariate import Dirichlet, LKJCholesky, MultivariateNormal
from .transform import (AbsTransform, AffineTransform, ChainTransform,
                        ExpTransform, IndependentTransform, PowerTransform,
                        ReshapeTransform, SigmoidTransform,
                        SoftmaxTransform, StackTransform,
                        StickBreakingTransform, TanhTransform, Transform)
from .transformed_distribution import Independent, TransformedDistribution
from .kl import kl_divergence, register_kl

__all__ = [
    "Distribution", "ExponentialFamily",
    "Beta", "Cauchy", "Chi2", "ContinuousBernoulli", "Exponential",
    "Gamma", "Gumbel", "Laplace", "LogNormal", "Normal", "StudentT",
    "Uniform",
    "Bernoulli", "Binomial", "Categorical", "Geometric", "Multinomial",
    "Poisson",
    "Dirichlet", "LKJCholesky", "MultivariateNormal",
    "Transform", "AbsTransform", "AffineTransform", "ChainTransform",
    "ExpTransform", "IndependentTransform", "PowerTransform",
    "ReshapeTransform", "SigmoidTransform", "SoftmaxTransform",
    "StackTransform", "StickBreakingTransform", "TanhTransform",
    "TransformedDistribution", "Independent",
    "kl_divergence", "register_kl",
]
