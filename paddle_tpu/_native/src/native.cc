// paddle_tpu native core: the systems-side components that the reference
// implements in C++ and that stay native in the TPU rebuild.
//
//  1. TCPStore  — master-based key-value rendezvous for multi-host bootstrap
//     (reference: paddle/phi/core/distributed/store/tcp_store.h:121,
//      store/store.h:24, socket.h). Used by paddle_tpu.distributed to
//     coordinate process groups / barriers the way the reference bootstraps
//     NCCL communicators; on TPU it complements jax.distributed's
//     coordination service with a user-level store (set/get/add/wait/barrier).
//
//  2. HostTracer — lock-minimal host event recorder behind RecordEvent
//     (reference: paddle/fluid/platform/profiler/host_tracer.h:26 and the
//      HostEventRecorder ring buffers). Thread-local buffers, steady-clock
//     nanoseconds, chrome-trace JSON export.
//
//  3. CommWatchdog — async collective timeout watchdog (reference:
//     paddle/phi/core/distributed/comm_task_manager.h:37,
//      nccl_comm_task.cc:129-186). Background thread polls registered
//     operations for deadline expiry and surfaces diagnostics instead of
//     hanging silently.
//
// Exposed via a plain C ABI (bound from Python with ctypes — no pybind11 in
// this image). All functions return 0 on success, negative errno-style codes
// on failure unless documented otherwise.

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

#define PT_EXPORT extern "C" __attribute__((visibility("default")))

namespace {

int64_t now_ns() {
  return std::chrono::duration_cast<std::chrono::nanoseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

// ---------------------------------------------------------------------------
// wire helpers: every message field is length-prefixed; all ints little-endian
// ---------------------------------------------------------------------------

bool send_all(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t w = ::send(fd, p, n, MSG_NOSIGNAL);
    if (w <= 0) {
      if (w < 0 && (errno == EINTR)) continue;
      return false;
    }
    p += w;
    n -= static_cast<size_t>(w);
  }
  return true;
}

bool recv_all(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r <= 0) {
      if (r < 0 && errno == EINTR) continue;
      return false;
    }
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool send_u32(int fd, uint32_t v) { return send_all(fd, &v, 4); }
bool recv_u32(int fd, uint32_t* v) { return recv_all(fd, v, 4); }
bool send_i64(int fd, int64_t v) { return send_all(fd, &v, 8); }
bool recv_i64(int fd, int64_t* v) { return recv_all(fd, v, 8); }

bool send_bytes(int fd, const std::string& s) {
  return send_u32(fd, static_cast<uint32_t>(s.size())) &&
         (s.empty() || send_all(fd, s.data(), s.size()));
}

bool recv_bytes(int fd, std::string* out, uint32_t max = 1u << 30) {
  uint32_t n;
  if (!recv_u32(fd, &n) || n > max) return false;
  out->resize(n);
  return n == 0 || recv_all(fd, &(*out)[0], n);
}

enum Cmd : uint8_t {
  kSet = 0,
  kGet = 1,      // blocking until key exists (server parks the connection)
  kAdd = 2,
  kWait = 3,     // blocking until key exists
  kCheck = 4,    // non-blocking existence probe
  kDelete = 5,
  kCompareSet = 6,
  kList = 7,
};

enum Status : uint8_t { kOk = 0, kTimeout = 1, kNotFound = 2, kError = 3 };

// ---------------------------------------------------------------------------
// TCPStore server
// ---------------------------------------------------------------------------

class StoreServer {
 public:
  explicit StoreServer(int port) {
    listen_fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    if (listen_fd_ < 0) throw std::runtime_error("socket() failed");
    int one = 1;
    ::setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_ANY);
    addr.sin_port = htons(static_cast<uint16_t>(port));
    if (::bind(listen_fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) <
        0) {
      ::close(listen_fd_);
      throw std::runtime_error("bind() failed");
    }
    socklen_t len = sizeof(addr);
    ::getsockname(listen_fd_, reinterpret_cast<sockaddr*>(&addr), &len);
    port_ = ntohs(addr.sin_port);
    ::listen(listen_fd_, 128);
    accept_thread_ = std::thread([this] { AcceptLoop(); });
  }

  ~StoreServer() { Stop(); }

  int port() const { return port_; }

  void Stop() {
    bool expected = false;
    if (!stopping_.compare_exchange_strong(expected, true)) return;
    ::shutdown(listen_fd_, SHUT_RDWR);
    ::close(listen_fd_);
    {
      // synchronize with WaitFor's predicate check so the notify can't be
      // lost between a waiter's pred evaluation and its block
      std::lock_guard<std::mutex> g(mu_);
    }
    cv_.notify_all();
    if (accept_thread_.joinable()) accept_thread_.join();
    std::vector<std::thread> workers;
    {
      std::lock_guard<std::mutex> g(threads_mu_);
      // unblock connection threads parked in recv()
      for (int fd : conn_fds_) ::shutdown(fd, SHUT_RDWR);
      workers.swap(conn_threads_);
    }
    for (auto& t : workers)
      if (t.joinable()) t.join();
  }

 private:
  void AcceptLoop() {
    while (!stopping_.load()) {
      int fd = ::accept(listen_fd_, nullptr, nullptr);
      if (fd < 0) {
        if (stopping_.load()) break;
        continue;
      }
      int one = 1;
      ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      std::lock_guard<std::mutex> g(threads_mu_);
      conn_fds_.push_back(fd);
      conn_threads_.emplace_back([this, fd] { Serve(fd); });
    }
  }

  void Serve(int fd) {
    while (!stopping_.load()) {
      uint8_t cmd;
      if (!recv_all(fd, &cmd, 1)) break;
      bool ok = true;
      switch (cmd) {
        case kSet: {
          std::string key, val;
          ok = recv_bytes(fd, &key) && recv_bytes(fd, &val);
          if (!ok) break;
          {
            std::lock_guard<std::mutex> g(mu_);
            data_[key] = std::move(val);
          }
          cv_.notify_all();
          uint8_t st = kOk;
          ok = send_all(fd, &st, 1);
          break;
        }
        case kGet:
        case kWait: {
          std::string key;
          int64_t timeout_ms;
          ok = recv_bytes(fd, &key) && recv_i64(fd, &timeout_ms);
          if (!ok) break;
          std::string val;
          uint8_t st = WaitFor(key, timeout_ms, &val);
          ok = send_all(fd, &st, 1);
          if (ok && cmd == kGet && st == kOk) ok = send_bytes(fd, val);
          break;
        }
        case kAdd: {
          std::string key;
          int64_t delta;
          ok = recv_bytes(fd, &key) && recv_i64(fd, &delta);
          if (!ok) break;
          int64_t result;
          {
            std::lock_guard<std::mutex> g(mu_);
            int64_t cur = 0;
            auto it = data_.find(key);
            if (it != data_.end() && !it->second.empty())
              cur = std::strtoll(it->second.c_str(), nullptr, 10);
            result = cur + delta;
            data_[key] = std::to_string(result);
          }
          cv_.notify_all();
          uint8_t st = kOk;
          ok = send_all(fd, &st, 1) && send_i64(fd, result);
          break;
        }
        case kCheck: {
          std::string key;
          ok = recv_bytes(fd, &key);
          if (!ok) break;
          uint8_t st;
          {
            std::lock_guard<std::mutex> g(mu_);
            st = data_.count(key) ? kOk : kNotFound;
          }
          ok = send_all(fd, &st, 1);
          break;
        }
        case kDelete: {
          std::string key;
          ok = recv_bytes(fd, &key);
          if (!ok) break;
          uint8_t st;
          {
            std::lock_guard<std::mutex> g(mu_);
            st = data_.erase(key) ? kOk : kNotFound;
          }
          ok = send_all(fd, &st, 1);
          break;
        }
        case kCompareSet: {
          std::string key, expect, desired;
          ok = recv_bytes(fd, &key) && recv_bytes(fd, &expect) &&
               recv_bytes(fd, &desired);
          if (!ok) break;
          std::string current;
          uint8_t st;
          {
            std::lock_guard<std::mutex> g(mu_);
            auto it = data_.find(key);
            if (it == data_.end()) {
              if (expect.empty()) {
                data_[key] = desired;
                current = desired;
                st = kOk;
              } else {
                st = kNotFound;
              }
            } else if (it->second == expect) {
              it->second = desired;
              current = desired;
              st = kOk;
            } else {
              current = it->second;
              st = kError;
            }
          }
          cv_.notify_all();
          ok = send_all(fd, &st, 1) && send_bytes(fd, current);
          break;
        }
        case kList: {
          std::string joined;
          {
            std::lock_guard<std::mutex> g(mu_);
            for (auto& kv : data_) {
              joined += kv.first;
              joined.push_back('\n');
            }
          }
          uint8_t st = kOk;
          ok = send_all(fd, &st, 1) && send_bytes(fd, joined);
          break;
        }
        default:
          ok = false;
      }
      if (!ok) break;
    }
    {
      // deregister before close so Stop() never shuts down a reused fd
      std::lock_guard<std::mutex> g(threads_mu_);
      conn_fds_.erase(std::remove(conn_fds_.begin(), conn_fds_.end(), fd),
                      conn_fds_.end());
    }
    ::close(fd);
  }

  uint8_t WaitFor(const std::string& key, int64_t timeout_ms,
                  std::string* out) {
    std::unique_lock<std::mutex> lk(mu_);
    auto pred = [&] { return stopping_.load() || data_.count(key) > 0; };
    if (timeout_ms < 0) {
      cv_.wait(lk, pred);
    } else if (!cv_.wait_for(lk, std::chrono::milliseconds(timeout_ms),
                             pred)) {
      return kTimeout;
    }
    if (stopping_.load() && !data_.count(key)) return kError;
    *out = data_[key];
    return kOk;
  }

  int listen_fd_ = -1;
  int port_ = 0;
  std::atomic<bool> stopping_{false};
  std::thread accept_thread_;
  std::mutex threads_mu_;
  std::vector<std::thread> conn_threads_;
  std::vector<int> conn_fds_;
  std::mutex mu_;
  std::condition_variable cv_;
  std::unordered_map<std::string, std::string> data_;
};

// ---------------------------------------------------------------------------
// TCPStore client
// ---------------------------------------------------------------------------

class StoreClient {
 public:
  StoreClient(const std::string& host, int port, int64_t timeout_ms) {
    addrinfo hints{};
    hints.ai_family = AF_INET;
    hints.ai_socktype = SOCK_STREAM;
    addrinfo* res = nullptr;
    if (::getaddrinfo(host.c_str(), std::to_string(port).c_str(), &hints,
                      &res) != 0 ||
        res == nullptr)
      throw std::runtime_error("getaddrinfo failed for " + host);
    int64_t deadline = now_ns() + timeout_ms * 1000000;
    int fd = -1;
    // retry-connect until the server side comes up (rendezvous semantics)
    while (true) {
      fd = ::socket(res->ai_family, res->ai_socktype, res->ai_protocol);
      if (fd >= 0 && ::connect(fd, res->ai_addr, res->ai_addrlen) == 0) break;
      if (fd >= 0) ::close(fd);
      fd = -1;
      if (now_ns() > deadline) break;
      std::this_thread::sleep_for(std::chrono::milliseconds(50));
    }
    ::freeaddrinfo(res);
    if (fd < 0) throw std::runtime_error("connect to store timed out");
    int one = 1;
    ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    fd_ = fd;
  }

  ~StoreClient() {
    if (fd_ >= 0) ::close(fd_);
  }

  int Set(const std::string& key, const std::string& val) {
    std::lock_guard<std::mutex> g(mu_);
    uint8_t cmd = kSet, st;
    if (!send_all(fd_, &cmd, 1) || !send_bytes(fd_, key) ||
        !send_bytes(fd_, val) || !recv_all(fd_, &st, 1))
      return -100;  // comm error
    return st == kOk ? 0 : -static_cast<int>(st);
  }

  int Get(const std::string& key, int64_t timeout_ms, std::string* out) {
    std::lock_guard<std::mutex> g(mu_);
    uint8_t cmd = kGet, st;
    if (!send_all(fd_, &cmd, 1) || !send_bytes(fd_, key) ||
        !send_i64(fd_, timeout_ms) || !recv_all(fd_, &st, 1))
      return -100;  // comm error
    if (st != kOk) return -static_cast<int>(st);
    return recv_bytes(fd_, out) ? 0 : -1;
  }

  int Add(const std::string& key, int64_t delta, int64_t* out) {
    std::lock_guard<std::mutex> g(mu_);
    uint8_t cmd = kAdd, st;
    if (!send_all(fd_, &cmd, 1) || !send_bytes(fd_, key) ||
        !send_i64(fd_, delta) || !recv_all(fd_, &st, 1) ||
        !recv_i64(fd_, out))
      return -100;  // comm error
    return 0;
  }

  int Wait(const std::string& key, int64_t timeout_ms) {
    std::lock_guard<std::mutex> g(mu_);
    uint8_t cmd = kWait, st;
    if (!send_all(fd_, &cmd, 1) || !send_bytes(fd_, key) ||
        !send_i64(fd_, timeout_ms) || !recv_all(fd_, &st, 1))
      return -100;  // comm error
    return st == kOk ? 0 : -static_cast<int>(st);
  }

  int Check(const std::string& key) {
    std::lock_guard<std::mutex> g(mu_);
    uint8_t cmd = kCheck, st;
    if (!send_all(fd_, &cmd, 1) || !send_bytes(fd_, key) ||
        !recv_all(fd_, &st, 1))
      return -100;  // comm error
    return st == kOk ? 1 : 0;
  }

  int Delete(const std::string& key) {
    std::lock_guard<std::mutex> g(mu_);
    uint8_t cmd = kDelete, st;
    if (!send_all(fd_, &cmd, 1) || !send_bytes(fd_, key) ||
        !recv_all(fd_, &st, 1))
      return -100;  // comm error
    return st == kOk ? 1 : 0;
  }

  int CompareSet(const std::string& key, const std::string& expect,
                 const std::string& desired, std::string* current) {
    std::lock_guard<std::mutex> g(mu_);
    uint8_t cmd = kCompareSet, st;
    if (!send_all(fd_, &cmd, 1) || !send_bytes(fd_, key) ||
        !send_bytes(fd_, expect) || !send_bytes(fd_, desired) ||
        !recv_all(fd_, &st, 1) || !recv_bytes(fd_, current))
      return -100;  // comm error
    return st == kOk ? 0 : -static_cast<int>(st);
  }

 private:
  int fd_ = -1;
  std::mutex mu_;  // one request in flight per client
};

// ---------------------------------------------------------------------------
// HostTracer: thread-local event buffers + chrome trace export
// ---------------------------------------------------------------------------

struct TraceEvent {
  std::string name;
  int64_t t_begin_ns;
  int64_t t_end_ns;  // -1 => counter event, value in t_begin? no: see kind
  uint64_t tid;
  int kind;  // 0 = duration, 1 = instant, 2 = counter
  double value;
};

class HostTracer {
 public:
  static HostTracer& Get() {
    static HostTracer t;
    return t;
  }

  void set_enabled(bool e) { enabled_.store(e); }
  bool enabled() const { return enabled_.load(std::memory_order_relaxed); }

  void Push(const char* name) {
    if (!enabled()) return;
    auto& tl = Local();
    tl.stack.emplace_back(name, now_ns());
  }

  void Pop() {
    if (!enabled()) return;
    auto& tl = Local();
    if (tl.stack.empty()) return;
    auto [name, begin] = std::move(tl.stack.back());
    tl.stack.pop_back();
    {
      std::lock_guard<std::mutex> g(tl.mu);
      tl.events.push_back(
          TraceEvent{std::move(name), begin, now_ns(), tl.tid, 0, 0.0});
    }
    MaybeFlush(tl);
  }

  void Instant(const char* name) {
    if (!enabled()) return;
    auto& tl = Local();
    int64_t t = now_ns();
    {
      std::lock_guard<std::mutex> g(tl.mu);
      tl.events.push_back(TraceEvent{name, t, t, tl.tid, 1, 0.0});
    }
    MaybeFlush(tl);
  }

  void Counter(const char* name, double value) {
    if (!enabled()) return;
    auto& tl = Local();
    int64_t t = now_ns();
    {
      std::lock_guard<std::mutex> g(tl.mu);
      tl.events.push_back(TraceEvent{name, t, t, tl.tid, 2, value});
    }
    MaybeFlush(tl);
  }

  void Clear() {
    std::lock_guard<std::mutex> g(mu_);
    global_.clear();
  }

  // chrome trace JSON (the "traceEvents" array content)
  std::string ExportChrome() {
    FlushAllRegistered();
    std::lock_guard<std::mutex> g(mu_);
    std::string out = "[";
    bool first = true;
    char buf[256];
    for (auto& e : global_) {
      if (!first) out += ",";
      first = false;
      const char* ph = e.kind == 0 ? "X" : (e.kind == 1 ? "i" : "C");
      out += "{\"name\":\"";
      AppendEscaped(&out, e.name);
      out += "\",\"ph\":\"";
      out += ph;
      out += "\",\"pid\":0,";
      snprintf(buf, sizeof(buf), "\"tid\":%llu,\"ts\":%.3f",
               static_cast<unsigned long long>(e.tid),
               static_cast<double>(e.t_begin_ns) / 1000.0);
      out += buf;
      if (e.kind == 0) {
        snprintf(buf, sizeof(buf), ",\"dur\":%.3f",
                 static_cast<double>(e.t_end_ns - e.t_begin_ns) / 1000.0);
        out += buf;
      } else if (e.kind == 2) {
        snprintf(buf, sizeof(buf), ",\"args\":{\"value\":%g}", e.value);
        out += buf;
      }
      out += "}";
    }
    out += "]";
    return out;
  }

  int64_t EventCount() {
    FlushAllRegistered();
    std::lock_guard<std::mutex> g(mu_);
    return static_cast<int64_t>(global_.size());
  }

 private:
  struct ThreadLocalBuf {
    std::mutex mu;  // guards events against cross-thread flush
    std::vector<std::pair<std::string, int64_t>> stack;
    std::vector<TraceEvent> events;
    uint64_t tid;
    HostTracer* owner = nullptr;
    ~ThreadLocalBuf() {
      if (owner) {
        owner->FlushThread(this);
        owner->Deregister(this);
      }
    }
  };

  ThreadLocalBuf& Local() {
    thread_local ThreadLocalBuf tl;
    if (!tl.owner) {
      tl.owner = this;
      static std::atomic<uint64_t> next_tid{1};
      tl.tid = next_tid.fetch_add(1);
      std::lock_guard<std::mutex> g(reg_mu_);
      registered_.push_back(&tl);
    }
    return tl;
  }

  void Deregister(ThreadLocalBuf* tl) {
    std::lock_guard<std::mutex> g(reg_mu_);
    registered_.erase(
        std::remove(registered_.begin(), registered_.end(), tl),
        registered_.end());
  }

  void MaybeFlush(ThreadLocalBuf& tl) {
    bool full;
    {
      std::lock_guard<std::mutex> g(tl.mu);
      full = tl.events.size() >= 4096;
    }
    if (full) FlushThread(&tl);
  }

  void FlushThread(ThreadLocalBuf* tl) {
    std::vector<TraceEvent> batch;
    {
      std::lock_guard<std::mutex> g(tl->mu);
      batch.swap(tl->events);
    }
    std::lock_guard<std::mutex> g(mu_);
    for (auto& e : batch) global_.push_back(std::move(e));
  }

  void FlushAllRegistered() {
    std::lock_guard<std::mutex> g(reg_mu_);
    for (auto* tl : registered_) FlushThread(tl);
  }

  static void AppendEscaped(std::string* out, const std::string& s) {
    for (char c : s) {
      if (c == '"' || c == '\\') {
        out->push_back('\\');
        out->push_back(c);
      } else if (static_cast<unsigned char>(c) >= 0x20) {
        out->push_back(c);
      }
    }
  }

  std::atomic<bool> enabled_{false};
  std::mutex mu_;
  std::deque<TraceEvent> global_;
  std::mutex reg_mu_;
  std::vector<ThreadLocalBuf*> registered_;
};

// ---------------------------------------------------------------------------
// CommWatchdog: deadline registry + poller thread
// ---------------------------------------------------------------------------

class CommWatchdog {
 public:
  static CommWatchdog& Get() {
    static CommWatchdog w;
    return w;
  }

  void Start(int64_t poll_ms) {
    std::lock_guard<std::mutex> g(mu_);
    poll_ms_ = poll_ms;
    if (running_) {
      // bump the interval epoch AND notify: the predicate form of
      // wait_for otherwise re-sleeps to its ORIGINAL deadline on a
      // spurious-looking wake, so a shorter interval would only apply
      // after the previous (possibly much longer) cycle ends
      epoch_++;
      cv_.notify_all();
      return;
    }
    running_ = true;
    thread_ = std::thread([this] { Loop(); });
  }

  void Stop() {
    {
      std::lock_guard<std::mutex> g(mu_);
      if (!running_) return;
      running_ = false;
    }
    cv_.notify_all();
    if (thread_.joinable()) thread_.join();
  }

  uint64_t Register(const char* desc, int64_t timeout_ms) {
    std::lock_guard<std::mutex> g(mu_);
    uint64_t id = next_id_++;
    ops_[id] = Op{desc ? desc : "", now_ns() + timeout_ms * 1000000, false};
    return id;
  }

  void Complete(uint64_t id) {
    std::lock_guard<std::mutex> g(mu_);
    ops_.erase(id);
  }

  int64_t ExpiredCount() {
    std::lock_guard<std::mutex> g(mu_);
    return expired_count_;
  }

  std::string LastExpired() {
    std::lock_guard<std::mutex> g(mu_);
    return last_expired_;
  }

 private:
  struct Op {
    std::string desc;
    int64_t deadline_ns;
    bool reported;
  };

  void Loop() {
    std::unique_lock<std::mutex> lk(mu_);
    uint64_t seen = epoch_;
    while (running_) {
      cv_.wait_for(lk, std::chrono::milliseconds(poll_ms_),
                   [&] { return !running_ || epoch_ != seen; });
      seen = epoch_;
      if (!running_) break;
      int64_t now = now_ns();
      for (auto& kv : ops_) {
        if (!kv.second.reported && now > kv.second.deadline_ns) {
          kv.second.reported = true;
          expired_count_++;
          last_expired_ = kv.second.desc;
          fprintf(stderr,
                  "[paddle_tpu watchdog] collective op '%s' exceeded its "
                  "timeout; the job may be hung (rank desync or network "
                  "failure).\n",
                  kv.second.desc.c_str());
        }
      }
    }
  }

  std::mutex mu_;
  std::condition_variable cv_;
  std::thread thread_;
  bool running_ = false;
  int64_t poll_ms_ = 1000;
  uint64_t next_id_ = 1;
  uint64_t epoch_ = 0;
  std::unordered_map<uint64_t, Op> ops_;
  int64_t expired_count_ = 0;
  std::string last_expired_;
};

}  // namespace

// ---------------------------------------------------------------------------
// C ABI
// ---------------------------------------------------------------------------

PT_EXPORT void* pt_store_server_start(int port) {
  try {
    return new StoreServer(port);
  } catch (...) {
    return nullptr;
  }
}

PT_EXPORT int pt_store_server_port(void* s) {
  return s ? static_cast<StoreServer*>(s)->port() : -1;
}

PT_EXPORT void pt_store_server_stop(void* s) {
  if (!s) return;
  auto* srv = static_cast<StoreServer*>(s);
  srv->Stop();
  delete srv;
}

PT_EXPORT void* pt_store_client_new(const char* host, int port,
                                    int64_t timeout_ms) {
  try {
    return new StoreClient(host ? host : "127.0.0.1", port, timeout_ms);
  } catch (...) {
    return nullptr;
  }
}

PT_EXPORT void pt_store_client_free(void* c) {
  delete static_cast<StoreClient*>(c);
}

PT_EXPORT int pt_store_set(void* c, const char* key, const uint8_t* data,
                           int64_t len) {
  if (!c) return -1;
  return static_cast<StoreClient*>(c)->Set(
      key, std::string(reinterpret_cast<const char*>(data),
                       static_cast<size_t>(len)));
}

// caller frees *out with pt_free
PT_EXPORT int pt_store_get(void* c, const char* key, int64_t timeout_ms,
                           uint8_t** out, int64_t* out_len) {
  if (!c) return -1;
  std::string val;
  int rc = static_cast<StoreClient*>(c)->Get(key, timeout_ms, &val);
  if (rc != 0) return rc;
  *out = static_cast<uint8_t*>(malloc(val.size() ? val.size() : 1));
  memcpy(*out, val.data(), val.size());
  *out_len = static_cast<int64_t>(val.size());
  return 0;
}

PT_EXPORT int pt_store_add(void* c, const char* key, int64_t delta,
                           int64_t* out) {
  if (!c) return -1;
  return static_cast<StoreClient*>(c)->Add(key, delta, out);
}

PT_EXPORT int pt_store_wait(void* c, const char* key, int64_t timeout_ms) {
  if (!c) return -1;
  return static_cast<StoreClient*>(c)->Wait(key, timeout_ms);
}

PT_EXPORT int pt_store_check(void* c, const char* key) {
  if (!c) return -1;
  return static_cast<StoreClient*>(c)->Check(key);
}

PT_EXPORT int pt_store_delete(void* c, const char* key) {
  if (!c) return -1;
  return static_cast<StoreClient*>(c)->Delete(key);
}

PT_EXPORT int pt_store_compare_set(void* c, const char* key,
                                   const uint8_t* expect, int64_t expect_len,
                                   const uint8_t* desired, int64_t desired_len,
                                   uint8_t** out, int64_t* out_len) {
  if (!c) return -1;
  std::string current;
  int rc = static_cast<StoreClient*>(c)->CompareSet(
      key,
      std::string(reinterpret_cast<const char*>(expect),
                  static_cast<size_t>(expect_len)),
      std::string(reinterpret_cast<const char*>(desired),
                  static_cast<size_t>(desired_len)),
      &current);
  *out = static_cast<uint8_t*>(malloc(current.size() ? current.size() : 1));
  memcpy(*out, current.data(), current.size());
  *out_len = static_cast<int64_t>(current.size());
  return rc;
}

PT_EXPORT void pt_free(void* p) { free(p); }

PT_EXPORT void pt_tracer_enable(int enabled) {
  HostTracer::Get().set_enabled(enabled != 0);
}

PT_EXPORT int pt_tracer_enabled() { return HostTracer::Get().enabled(); }

PT_EXPORT void pt_tracer_push(const char* name) {
  HostTracer::Get().Push(name);
}

PT_EXPORT void pt_tracer_pop() { HostTracer::Get().Pop(); }

PT_EXPORT void pt_tracer_instant(const char* name) {
  HostTracer::Get().Instant(name);
}

PT_EXPORT void pt_tracer_counter(const char* name, double value) {
  HostTracer::Get().Counter(name, value);
}

PT_EXPORT void pt_tracer_clear() { HostTracer::Get().Clear(); }

PT_EXPORT int64_t pt_tracer_event_count() {
  return HostTracer::Get().EventCount();
}

// caller frees with pt_free
PT_EXPORT int pt_tracer_export_chrome(uint8_t** out, int64_t* out_len) {
  std::string s = HostTracer::Get().ExportChrome();
  *out = static_cast<uint8_t*>(malloc(s.size() ? s.size() : 1));
  memcpy(*out, s.data(), s.size());
  *out_len = static_cast<int64_t>(s.size());
  return 0;
}

PT_EXPORT void pt_watchdog_start(int64_t poll_ms) {
  CommWatchdog::Get().Start(poll_ms);
}

PT_EXPORT void pt_watchdog_stop() { CommWatchdog::Get().Stop(); }

PT_EXPORT uint64_t pt_watchdog_register(const char* desc,
                                        int64_t timeout_ms) {
  return CommWatchdog::Get().Register(desc, timeout_ms);
}

PT_EXPORT void pt_watchdog_complete(uint64_t id) {
  CommWatchdog::Get().Complete(id);
}

PT_EXPORT int64_t pt_watchdog_expired_count() {
  return CommWatchdog::Get().ExpiredCount();
}

// caller frees *out with pt_free
PT_EXPORT void pt_watchdog_last_expired(uint8_t** out, int64_t* out_len) {
  std::string s = CommWatchdog::Get().LastExpired();
  *out = static_cast<uint8_t*>(malloc(s.size() ? s.size() : 1));
  memcpy(*out, s.data(), s.size());
  *out_len = static_cast<int64_t>(s.size());
}

PT_EXPORT int pt_version() { return 1; }
