"""Native (C++) core loader.

The reference keeps its systems layer in C++ (TCPStore rendezvous —
paddle/phi/core/distributed/store/tcp_store.h:121; host profiler recorder —
paddle/fluid/platform/profiler/host_tracer.h:26; collective watchdog —
paddle/phi/core/distributed/comm_task_manager.h:37). paddle_tpu does the
same: `src/native.cc` is compiled once into a shared library and bound via
ctypes (pybind11 is not in this image). The build is cached next to the
source keyed on a content hash; if no C++ toolchain is available the
`available()` probe returns False and pure-Python fallbacks take over
(paddle_tpu.distributed.store / paddle_tpu.profiler handle that).
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import sys
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "src", "native.cc")
_BUILD_DIR = os.path.join(_DIR, "_build")

_lock = threading.Lock()
_lib = None
_tried = False


def _lib_path() -> str:
    with open(_SRC, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    return os.path.join(_BUILD_DIR, f"libpaddle_tpu_native_{digest}.so")


def _build(path: str) -> bool:
    os.makedirs(_BUILD_DIR, exist_ok=True)
    tmp = path + f".tmp{os.getpid()}"
    cmd = [
        "g++", "-std=c++17", "-O2", "-fPIC", "-shared", "-pthread",
        "-fvisibility=hidden", _SRC, "-o", tmp,
    ]
    try:
        res = subprocess.run(cmd, capture_output=True, timeout=240)
    except (OSError, subprocess.TimeoutExpired):
        return False
    if res.returncode != 0:
        sys.stderr.write(
            "paddle_tpu: native build failed, using Python fallbacks:\n"
            + res.stderr.decode(errors="replace")[-2000:] + "\n")
        return False
    os.replace(tmp, path)  # atomic: concurrent builders race benignly
    return True


def load():
    """Return the ctypes CDLL for the native core, or None."""
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        if os.environ.get("PADDLE_TPU_DISABLE_NATIVE"):
            return None
        path = _lib_path()
        if not os.path.exists(path) and not _build(path):
            return None
        try:
            lib = ctypes.CDLL(path)
        except OSError:
            return None
        _declare(lib)
        _lib = lib
        return _lib


def available() -> bool:
    return load() is not None


def _declare(lib):
    c = ctypes
    u8p = c.POINTER(c.c_uint8)

    lib.pt_store_server_start.restype = c.c_void_p
    lib.pt_store_server_start.argtypes = [c.c_int]
    lib.pt_store_server_port.restype = c.c_int
    lib.pt_store_server_port.argtypes = [c.c_void_p]
    lib.pt_store_server_stop.restype = None
    lib.pt_store_server_stop.argtypes = [c.c_void_p]

    lib.pt_store_client_new.restype = c.c_void_p
    lib.pt_store_client_new.argtypes = [c.c_char_p, c.c_int, c.c_int64]
    lib.pt_store_client_free.restype = None
    lib.pt_store_client_free.argtypes = [c.c_void_p]
    lib.pt_store_set.restype = c.c_int
    lib.pt_store_set.argtypes = [c.c_void_p, c.c_char_p, u8p, c.c_int64]
    lib.pt_store_get.restype = c.c_int
    lib.pt_store_get.argtypes = [c.c_void_p, c.c_char_p, c.c_int64,
                                 c.POINTER(u8p), c.POINTER(c.c_int64)]
    lib.pt_store_add.restype = c.c_int
    lib.pt_store_add.argtypes = [c.c_void_p, c.c_char_p, c.c_int64,
                                 c.POINTER(c.c_int64)]
    lib.pt_store_wait.restype = c.c_int
    lib.pt_store_wait.argtypes = [c.c_void_p, c.c_char_p, c.c_int64]
    lib.pt_store_check.restype = c.c_int
    lib.pt_store_check.argtypes = [c.c_void_p, c.c_char_p]
    lib.pt_store_delete.restype = c.c_int
    lib.pt_store_delete.argtypes = [c.c_void_p, c.c_char_p]
    lib.pt_store_compare_set.restype = c.c_int
    lib.pt_store_compare_set.argtypes = [
        c.c_void_p, c.c_char_p, u8p, c.c_int64, u8p, c.c_int64,
        c.POINTER(u8p), c.POINTER(c.c_int64)]
    lib.pt_free.restype = None
    lib.pt_free.argtypes = [c.c_void_p]

    lib.pt_tracer_enable.restype = None
    lib.pt_tracer_enable.argtypes = [c.c_int]
    lib.pt_tracer_enabled.restype = c.c_int
    lib.pt_tracer_push.restype = None
    lib.pt_tracer_push.argtypes = [c.c_char_p]
    lib.pt_tracer_pop.restype = None
    lib.pt_tracer_instant.restype = None
    lib.pt_tracer_instant.argtypes = [c.c_char_p]
    lib.pt_tracer_counter.restype = None
    lib.pt_tracer_counter.argtypes = [c.c_char_p, c.c_double]
    lib.pt_tracer_clear.restype = None
    lib.pt_tracer_event_count.restype = c.c_int64
    lib.pt_tracer_export_chrome.restype = c.c_int
    lib.pt_tracer_export_chrome.argtypes = [c.POINTER(u8p),
                                            c.POINTER(c.c_int64)]

    lib.pt_watchdog_start.restype = None
    lib.pt_watchdog_start.argtypes = [c.c_int64]
    lib.pt_watchdog_stop.restype = None
    lib.pt_watchdog_register.restype = c.c_uint64
    lib.pt_watchdog_register.argtypes = [c.c_char_p, c.c_int64]
    lib.pt_watchdog_complete.restype = None
    lib.pt_watchdog_complete.argtypes = [c.c_uint64]
    lib.pt_watchdog_expired_count.restype = c.c_int64
    lib.pt_watchdog_last_expired.restype = None
    lib.pt_watchdog_last_expired.argtypes = [c.POINTER(u8p),
                                             c.POINTER(c.c_int64)]


def _take_bytes(lib, out_p, out_len):
    """Copy a (ptr,len) result into bytes and free the native buffer."""
    try:
        if not out_p or out_len.value < 0:
            return b""
        return ctypes.string_at(out_p, out_len.value)
    finally:
        if out_p:
            lib.pt_free(out_p)
