"""Dev tool: capture an XLA profiler trace of the bench train step and
print a per-op-category device-time breakdown.

Usage: python tools/trace_step.py [outdir]
The trace (tensorboard format) lands in outdir (default /tmp/ptpu_trace);
the summary groups device events by HLO op-name prefix so the glue
(copies/reshapes/broadcasts) is visible next to matmuls and the Pallas
attention kernels.
"""
from __future__ import annotations

import glob
import gzip
import json
import os
import sys
from collections import defaultdict

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def build_step():
    import numpy as np
    import paddle_tpu
    import paddle_tpu.optimizer as opt
    from paddle_tpu.models import LlamaForCausalLM, LlamaConfig
    from paddle_tpu.parallel import Trainer, TrainStepConfig

    cfg = LlamaConfig(
        vocab_size=32000, hidden_size=1280, intermediate_size=3584,
        num_hidden_layers=16, num_attention_heads=20,
        num_key_value_heads=4, max_position_embeddings=2048,
        rope_theta=10000.0, seq_length=2048, recompute=False,
        use_flash_attention=True,
        fuse_attention_qkv=True, fuse_attention_ffn=False)
    paddle_tpu.seed(0)
    model = LlamaForCausalLM(cfg)
    optimizer = opt.AdamW(learning_rate=1e-4,
                          parameters=model.parameters(), weight_decay=0.01)
    trainer = Trainer(model, optimizer,
                      config=TrainStepConfig(compute_dtype="bfloat16"))
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (6, 2048)).astype(np.int32)
    data = {"input_ids": ids, "labels": ids}
    return trainer, data


def capture(outdir):
    import jax
    trainer, data = build_step()
    float(trainer.step(data))           # compile + warmup
    with jax.profiler.trace(outdir):
        for _ in range(3):
            loss = trainer.step(data)
        float(loss)


def summarize(outdir, top=40):
    paths = glob.glob(os.path.join(
        outdir, "plugins/profile/*/*.trace.json.gz"))
    if not paths:
        print("no trace.json.gz found under", outdir)
        return
    path = max(paths, key=os.path.getmtime)
    with gzip.open(path, "rt") as f:
        trace = json.load(f)
    events = trace.get("traceEvents", [])
    # device events live on TPU pids; find pids whose name mentions TPU/XLA
    pid_name = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            pid_name[e["pid"]] = e["args"].get("name", "")
    dev_pids = {p for p, n in pid_name.items()
                if "TPU" in n or "/device" in n.lower()}
    import re
    tot = defaultdict(float)
    cnt = defaultdict(int)
    fam = defaultdict(float)
    for e in events:
        if e.get("ph") != "X" or e.get("pid") not in dev_pids:
            continue
        name = e.get("name", "")
        # skip aggregate lanes: bare step numbers and the jit_step span
        if re.fullmatch(r"\d+", name) or name.startswith("jit_"):
            continue
        us = e.get("dur", 0)
        tot[name] += us
        cnt[name] += 1
        fam[re.sub(r"[.\d]+$", "", name)] += us
    grand = sum(tot.values())
    print(f"trace: {path}")
    print(f"total device op time: {grand/1000:.2f} ms over 3 steps "
          f"(= {grand/3000:.2f} ms/step)\n")
    print("-- by op family --")
    print(f"{'family':48s} {'ms/step':>9s} {'%':>6s}")
    for name, us in sorted(fam.items(), key=lambda kv: -kv[1])[:25]:
        print(f"{name[:48]:48s} {us/3000:9.3f} {100*us/grand:5.1f}%")
    print("\n-- top individual ops --")
    print(f"{'op':62s} {'ms/step':>9s} {'count':>6s} {'%':>6s}")
    for name, us in sorted(tot.items(), key=lambda kv: -kv[1])[:top]:
        print(f"{name[:62]:62s} {us/3000:9.3f} {cnt[name]:6d} "
              f"{100*us/grand:5.1f}%")


if __name__ == "__main__":
    outdir = sys.argv[1] if len(sys.argv) > 1 else "/tmp/ptpu_trace"
    capture(outdir)
    summarize(outdir)
