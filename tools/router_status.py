"""Render a ReplicaRouter's live fleet view as a terminal table.

    python -m tools.router_status http://127.0.0.1:8900 [--json]

Fetches `GET /debug/replicas` and `GET /stats` from a running
`paddle_tpu.inference.router.ReplicaRouter` and prints the per-replica
rotation state, reason, probe counters, load numbers, and breaker
state — the operator's one-glance answer to "why is traffic not
reaching replica 3". `--json` dumps the raw merged document instead
(for scripts).

Stdlib-only (no jax, no paddle_tpu import): this runs on any box that
can reach the router.
"""
from __future__ import annotations

import json
import sys
import urllib.request

__all__ = ["fetch", "render", "main"]


def fetch(base_url, timeout=5.0) -> dict:
    """{"replicas": [...], "summary": {...}, "stats": {...}} from a
    live router. A failed /stats never sinks the replica table."""
    base = base_url.rstrip("/")
    if not base.startswith("http"):
        base = "http://" + base
    with urllib.request.urlopen(base + "/debug/replicas",
                                timeout=timeout) as resp:
        doc = json.loads(resp.read())
    try:
        with urllib.request.urlopen(base + "/stats",
                                    timeout=timeout) as resp:
            doc["stats"] = json.loads(resp.read())
    except Exception as e:      # noqa: BLE001 — stats are garnish
        doc["stats"] = {"error": repr(e)}
    return doc


def _fmt(v):
    if v is None:
        return "-"
    if isinstance(v, bool):
        return "yes" if v else "no"
    if isinstance(v, float):
        return f"{v:.3g}"
    return str(v)


def render(doc) -> str:
    """The /debug/replicas (+stats) document as an aligned table +
    summary lines. Tolerates missing keys: a half-broken router still
    renders what it returned."""
    rows = doc.get("replicas") or []
    cols = [("id", "id"), ("role", "role"), ("rot", "in_rotation"),
            ("depri", "deprioritized"), ("reason", "reason"),
            ("ok", "consecutive_ok"), ("fail", "consecutive_fail"),
            ("load", "load_score"), ("inflight", "replica_in_flight"),
            ("queue", "replica_queue_depth"),
            ("breaker", None), ("eject", "ejections"),
            ("served", "served"), ("pfx_hit", "prefix_hit_rate"),
            ("tier_hit", "kvtier_hit_rate"),
            ("probe_age", "last_probe_age_s")]
    table = [[h for h, _k in cols]]
    for r in rows:
        cells = []
        for _h, k in cols:
            if k is None:
                cells.append(_fmt((r.get("breaker") or {}).get("state")))
            else:
                cells.append(_fmt(r.get(k)))
        table.append(cells)
    widths = [max(len(row[i]) for row in table)
              for i in range(len(cols))]
    lines = ["  ".join(c.ljust(w) for c, w in zip(row, widths)).rstrip()
             for row in table]
    s = doc.get("summary") or {}
    lines.append("")
    lines.append(
        f"replicas: {_fmt(s.get('total'))} total, "
        f"{_fmt(s.get('in_rotation'))} in rotation, "
        f"{_fmt(s.get('ejected'))} ejected, "
        f"{_fmt(s.get('deprioritized'))} deprioritized; "
        f"sessions pinned: {_fmt(s.get('sessions'))}; "
        f"prefix pins: {_fmt(s.get('prefix_pins'))}")
    pools = s.get("pools")
    if isinstance(pools, dict):
        lines.append(f"pools: {_fmt(pools.get('prefill'))} prefill, "
                     f"{_fmt(pools.get('decode'))} decode")
    # handoff volume, summed over the per-replica disagg blocks the
    # probe collected (prefill replicas export, decode ones import)
    disagg = [r.get("disagg") for r in rows
              if isinstance(r.get("disagg"), dict)]
    if disagg:
        out_b = sum(d.get("handoff_bytes", 0) for d in disagg)
        in_b = sum(d.get("imported_bytes", 0) for d in disagg)
        deduped = sum(d.get("dedup_skipped_pages", 0) for d in disagg)
        fails = sum(d.get("pull_failures", 0) for d in disagg)
        lines.append(f"handoff: {out_b} bytes exported, "
                     f"{in_b} bytes imported, "
                     f"{deduped} pages dedup-skipped, "
                     f"{fails} pull failures")
    stats = doc.get("stats")
    if isinstance(stats, dict) and "error" not in stats:
        lines.append(f"requests: {stats.get('requests') or {}}  "
                     f"retries: {stats.get('retries') or {}}")
    return "\n".join(lines)


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    as_json = "--json" in argv
    argv = [a for a in argv if a != "--json"]
    if len(argv) != 1:
        print(__doc__.strip().splitlines()[2].strip(), file=sys.stderr)
        return 2
    try:
        doc = fetch(argv[0])
    except Exception as e:      # noqa: BLE001 — CLI boundary: report, don't traceback
        print(f"error: cannot reach router at {argv[0]}: {e!r}",
              file=sys.stderr)
        return 1
    print(json.dumps(doc, indent=1, sort_keys=True) if as_json
          else render(doc))
    return 0


if __name__ == "__main__":
    sys.exit(main())
