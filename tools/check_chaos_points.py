#!/usr/bin/env python
"""Fail CI when a chaos injection point is missing from the registry.

`distributed/chaos.py` carries POINTS, the documented registry of every
named fault-injection site. An injection call whose site literal is not
registered is invisible to operators reading the catalogue (and to the
README's knob table), so this checker walks every
`chaos.should_fire/maybe_delay/maybe_drop/maybe_preempt/
maybe_corrupt_file/grad_poison("site")` call in paddle_tpu/ and fails
if:

  - the literal site name has no POINTS entry (registry keys ending in
    "/" cover dynamically-suffixed f-string sites by static prefix), or
  - the site argument is not a string literal / f-string at all (a
    variable cannot be audited against the registry).

Usage: python tools/check_chaos_points.py [root]
Exit 0 = clean, 1 = undocumented or unauditable sites found. Stale
registry entries (documented but never called) are reported as a
warning without failing — a point may be mid-migration.

Wired into the tier-1 flow via tests/test_chaos_points_tool.py (the
same pattern as tools/check_jax_compat.py).
"""
from __future__ import annotations

import ast
import importlib.util
import os
import sys

INJECTORS = {"should_fire", "maybe_delay", "maybe_drop",
             "maybe_preempt", "maybe_corrupt_file", "grad_poison"}

# the registry module itself (its function bodies pass `site` variables
# around, which is the implementation, not an injection site)
ALLOWED = {os.path.join("paddle_tpu", "distributed", "chaos.py")}


def _load_points(root: str) -> dict:
    path = os.path.join(root, "paddle_tpu", "distributed", "chaos.py")
    spec = importlib.util.spec_from_file_location("_chaos_registry", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)        # stdlib-only module (no jax)
    return dict(getattr(mod, "POINTS", {}))


def _site_of(node):
    """(site, is_prefix) of an injection call's first argument, or
    (None, False) when it is not a literal. An f-string yields its
    static leading text as a prefix."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value, False
    if isinstance(node, ast.JoinedStr):
        if node.values and isinstance(node.values[0], ast.Constant) \
                and isinstance(node.values[0].value, str):
            return node.values[0].value, True
        return None, False
    return None, False


def _covered(site: str, is_prefix: bool, points: dict) -> bool:
    if not is_prefix:
        return site in points or any(
            k.endswith("/") and site.startswith(k) for k in points)
    # an f-string's static prefix must match a registered prefix key
    return any(k.endswith("/") and site.startswith(k) for k in points)


def scan(root: str):
    """Yield (relpath, lineno, call, problem) for every violation, and
    also return the set of sites seen (for stale-entry reporting) via
    the second element of the (violations, seen) tuple."""
    points = _load_points(root)
    pkg = os.path.join(root, "paddle_tpu")
    violations = []
    seen = set()
    for dirpath, _dirnames, filenames in os.walk(pkg):
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, root)
            if rel in ALLOWED:
                continue
            try:
                with open(path, encoding="utf-8") as f:
                    tree = ast.parse(f.read(), filename=rel)
            except (OSError, SyntaxError):
                continue
            for node in ast.walk(tree):
                if not isinstance(node, ast.Call):
                    continue
                func = node.func
                name = (func.attr if isinstance(func, ast.Attribute)
                        else func.id if isinstance(func, ast.Name)
                        else None)
                if name not in INJECTORS or not node.args:
                    continue
                site, is_prefix = _site_of(node.args[0])
                call = f"{name}({ast.unparse(node.args[0])})"
                if site is None:
                    violations.append(
                        (rel, node.lineno, call,
                         "site is not a string literal / f-string — "
                         "cannot be audited against chaos.POINTS"))
                    continue
                seen.add((site, is_prefix))
                if not _covered(site, is_prefix, points):
                    violations.append(
                        (rel, node.lineno, call,
                         f"site {site!r} is not in the chaos.POINTS "
                         "registry (distributed/chaos.py) — document "
                         "it there"))
    return violations, seen, points


def main(argv):
    root = argv[1] if len(argv) > 1 else \
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    violations, seen, points = scan(root)
    if violations:
        print(f"check_chaos_points: {len(violations)} undocumented "
              "chaos injection site(s):", file=sys.stderr)
        for rel, no, call, why in violations:
            print(f"  {rel}:{no}: {call}\n      -> {why}",
                  file=sys.stderr)
        return 1
    flat = {s for s, _p in seen}
    stale = [k for k in points
             if k not in flat
             and not (k.endswith("/")
                      and any(s.startswith(k) for s in flat))]
    if stale:
        # warn only: a documented-but-uncalled point may be mid-move
        print("check_chaos_points: warning, registry entries with no "
              f"call site: {sorted(stale)}")
    print(f"check_chaos_points: clean ({len(flat)} literal site(s) "
          f"across the package, {len(points)} registered)")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
