#!/usr/bin/env python
"""Fail CI when a chaos injection point is missing from the registry.

THIN SHIM: the scanner now lives in the unified static-analysis
framework as the `chaos-points` pass
(tools/analyze/passes/chaos_points.py) and runs with the full suite via
`python -m tools.analyze`. This CLI (and its `scan(root)` surface, used
by tests/test_chaos_points_tool.py) is kept so nothing downstream
breaks.

Usage: python tools/check_chaos_points.py [root]
Exit 0 = clean, 1 = undocumented or unauditable sites found. Stale
registry entries (documented but never called) are reported as a
warning without failing — a point may be mid-migration.
"""
from __future__ import annotations

import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

from tools.analyze.passes.chaos_points import (  # noqa: E402,F401
    ALLOWED, INJECTORS, scan)


def main(argv):
    root = argv[1] if len(argv) > 1 else _ROOT
    violations, seen, points = scan(root)
    if violations:
        print(f"check_chaos_points: {len(violations)} undocumented "
              "chaos injection site(s):", file=sys.stderr)
        for rel, no, call, why in violations:
            print(f"  {rel}:{no}: {call}\n      -> {why}",
                  file=sys.stderr)
        return 1
    flat = {s for s, _p in seen}
    stale = [k for k in points
             if k not in flat
             and not (k.endswith("/")
                      and any(s.startswith(k) for s in flat))]
    if stale:
        # warn only: a documented-but-uncalled point may be mid-move
        print("check_chaos_points: warning, registry entries with no "
              f"call site: {sorted(stale)}")
    print(f"check_chaos_points: clean ({len(flat)} literal site(s) "
          f"across the package, {len(points)} registered)")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
