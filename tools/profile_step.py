"""Dev tool: break the bench train step into timed components on the
attached chip. The axon tunnel costs ~5-7ms per dispatch, so each
component is repeated REPS times INSIDE one jit (lax.scan chained) and
the whole thing timed with a single host sync.

Usage: python tools/profile_step.py [part ...]
Parts: step flash sdpa ce embed raw  (default: all)
"""
from __future__ import annotations

import sys
import time

import numpy as np
import jax
import jax.numpy as jnp

REPS = 16


def sync(out):
    """Block until `out` is done, transferring only one scalar."""
    leaf = jax.tree.leaves(out)[0]
    float(jnp.sum(leaf.ravel()[:1].astype(jnp.float32)))


def timed(fn, *args, name="", reps=REPS):
    """fn(*args) -> pytree; fn already contains `reps` repetitions."""
    sync(fn(*args))
    t0 = time.perf_counter()
    sync(fn(*args))
    dt = (time.perf_counter() - t0) / reps * 1000
    print(f"{name:38s} {dt:8.2f} ms")
    return dt


def chain(op, x0, reps=REPS):
    """Apply y = op(x) reps times inside one jit, feeding back a scalar
    perturbation so nothing is DCE'd or CSE'd."""
    def body(x, _):
        y = op(x)
        leaf = jax.tree.leaves(y)[0]
        bump = (leaf.ravel()[0]).astype(x.dtype) * 1e-20
        return x + bump, None

    return jax.jit(lambda x: jax.lax.scan(body, x, None, length=reps)[0])


def bench_cfg():
    from paddle_tpu.models import LlamaConfig
    return LlamaConfig(
        vocab_size=32000, hidden_size=1280, intermediate_size=3584,
        num_hidden_layers=16, num_attention_heads=20,
        num_key_value_heads=4, max_position_embeddings=2048,
        rope_theta=10000.0, seq_length=2048, recompute=False,
        use_flash_attention=True)


B, S = 4, 2048


def part_step():
    import paddle_tpu
    import paddle_tpu.optimizer as opt
    from paddle_tpu.models import LlamaForCausalLM
    from paddle_tpu.parallel import Trainer, TrainStepConfig
    cfg = bench_cfg()
    paddle_tpu.seed(0)
    model = LlamaForCausalLM(cfg)
    optimizer = opt.AdamW(learning_rate=1e-4,
                          parameters=model.parameters(), weight_decay=0.01)
    trainer = Trainer(model, optimizer,
                      config=TrainStepConfig(compute_dtype="bfloat16"))
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (B, S)).astype(np.int32)
    data = {"input_ids": ids, "labels": ids}
    trainer.step(data)
    np.asarray(trainer.params["model.norm.weight"]).ravel()[:1]
    t0 = time.perf_counter()
    n = 10
    for _ in range(n):
        trainer.step(data)
    np.asarray(trainer.params["model.norm.weight"]).ravel()[:1]
    dt = (time.perf_counter() - t0) / n * 1000
    print(f"{'full trainer step':38s} {dt:8.2f} ms")


def _attn_shapes():
    cfg = bench_cfg()
    hq, hk, d = cfg.num_attention_heads, cfg.num_key_value_heads, cfg.head_dim
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(k1, (B, hq, S, d), jnp.bfloat16)
    k = jax.random.normal(k2, (B, hk, S, d), jnp.bfloat16)
    v = jax.random.normal(k3, (B, hk, S, d), jnp.bfloat16)
    return q, k, v


def part_flash():
    from paddle_tpu.kernels.flash_attention import flash_attention_bhsd
    q, k, v = _attn_shapes()
    with jax.default_matmul_precision("default"):
        f = chain(lambda q: flash_attention_bhsd(q, k, v, causal=True)
                  .astype(q.dtype), q)
        timed(f, q, name="flash fwd (1 layer)")

        def fb(q):
            def loss(q, k, v):
                return flash_attention_bhsd(q, k, v, causal=True).astype(
                    jnp.float32).sum()
            g = jax.grad(loss, argnums=(0,))(q, k, v)[0]
            return g.astype(q.dtype)
        timed(chain(fb, q), q, name="flash fwd+bwd (1 layer)")


def part_sdpa():
    import paddle_tpu  # noqa: F401  (match package-global precision env)
    q, k, v = _attn_shapes()

    def sdpa(q, k, v):
        hq, hk = q.shape[1], k.shape[1]
        kk = jnp.repeat(k, hq // hk, axis=1)
        vv = jnp.repeat(v, hq // hk, axis=1)
        s = jnp.einsum("bhqd,bhkd->bhqk", q, kk,
                       preferred_element_type=jnp.float32)
        s = s / np.sqrt(q.shape[-1])
        mask = jnp.tril(jnp.ones((S, S), bool))
        s = jnp.where(mask, s, -1e30)
        p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
        return jnp.einsum("bhqk,bhkd->bhqd", p, vv,
                          preferred_element_type=jnp.float32).astype(q.dtype)

    with jax.default_matmul_precision("default"):
        timed(chain(lambda q: sdpa(q, k, v), q), q, name="sdpa fwd (1 layer)")

        def fb(q):
            g = jax.grad(lambda q: sdpa(q, k, v).astype(jnp.float32).sum())(q)
            return g.astype(q.dtype)
        timed(chain(fb, q), q, name="sdpa fwd+bwd (1 layer)")


def part_ce():
    cfg = bench_cfg()
    n, d, vsz = B * S, cfg.hidden_size, cfg.vocab_size
    k1, k2 = jax.random.split(jax.random.PRNGKey(0))
    h = jax.random.normal(k1, (n, d), jnp.bfloat16)
    w = jax.random.normal(k2, (d, vsz), jnp.bfloat16)
    y = jax.random.randint(jax.random.PRNGKey(1), (n,), 0, vsz)

    def ce_raw(h, w):
        logits = (h @ w).astype(jnp.float32)
        ls = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.take_along_axis(ls, y[:, None], axis=-1).mean()

    import paddle_tpu  # noqa: F401
    from paddle_tpu.nn import functional as F
    from paddle_tpu.core.tensor import Tensor

    def ce_ours(h, w):
        logits = h @ w
        t = F.cross_entropy(Tensor(logits.reshape(-1, vsz)),
                            Tensor(y.reshape(-1)), reduction="mean")
        return t._value

    with jax.default_matmul_precision("default"):
        timed(chain(lambda h: h + ce_raw(h, w).astype(h.dtype) * 0, h),
              h, name="lm_head+CE fwd (raw)")
        timed(chain(lambda h: jax.grad(ce_raw)(h, w).astype(h.dtype), h),
              h, name="lm_head+CE fwd+bwd_h (raw)")
        timed(chain(lambda h: jax.grad(ce_ours)(h, w).astype(h.dtype), h),
              h, name="lm_head+CE fwd+bwd_h (ours)")

        def both(h):
            gh, gw = jax.grad(ce_ours, argnums=(0, 1))(h, w)
            return gh.astype(h.dtype)
        timed(chain(both, h), h, name="lm_head+CE fwd+bwd_hw (ours)")


def part_embed():
    cfg = bench_cfg()
    vsz, d = cfg.vocab_size, cfg.hidden_size
    tab = jax.random.normal(jax.random.PRNGKey(0), (vsz, d), jnp.float32)
    ids = jax.random.randint(jax.random.PRNGKey(1), (B, S), 0, vsz)

    def emb(tab):
        return tab[ids].astype(jnp.float32).sum()

    def emb_onehot(tab):
        oh = jax.nn.one_hot(ids.reshape(-1), vsz, dtype=jnp.bfloat16)
        return (oh @ tab.astype(jnp.bfloat16)).astype(jnp.float32).sum()

    with jax.default_matmul_precision("default"):
        timed(chain(lambda t: jax.grad(emb)(t), tab),
              tab, name="embed fwd+bwd (take+scatter)")
        timed(chain(lambda t: jax.grad(emb_onehot)(t), tab),
              tab, name="embed fwd+bwd (onehot matmul)")


def part_raw():
    """Dense-stack-equivalent fwd+bwd in raw jax (lower bound), REPS=1
    since the stack itself is 16 layers."""
    cfg = bench_cfg()
    d, f, L = cfg.hidden_size, cfg.intermediate_size, cfg.num_hidden_layers
    ks = jax.random.split(jax.random.PRNGKey(0), 8)
    x = jax.random.normal(ks[0], (B * S, d), jnp.bfloat16)
    Wq = jax.random.normal(ks[1], (L, d, d), jnp.bfloat16) * 0.02
    Wo = jax.random.normal(ks[3], (L, d, d), jnp.bfloat16) * 0.02
    W1 = jax.random.normal(ks[4], (L, d, f), jnp.bfloat16) * 0.02
    W2 = jax.random.normal(ks[5], (L, d, f), jnp.bfloat16) * 0.02
    W3 = jax.random.normal(ks[6], (L, f, d), jnp.bfloat16) * 0.02

    def fwd(x, Wq, Wo, W1, W2, W3):
        def layer(x, ws):
            wq, wo, w1, w2, w3 = ws
            a = x @ wq
            x = x + a @ wo
            h = jax.nn.silu(x @ w1) * (x @ w2)
            return x + h @ w3, None
        x, _ = jax.lax.scan(layer, x, (Wq, Wo, W1, W2, W3))
        return x.astype(jnp.float32).sum()

    with jax.default_matmul_precision("default"):
        g = jax.jit(jax.grad(fwd, argnums=(0, 1, 2, 3, 4, 5)))
        timed(g, x, Wq, Wo, W1, W2, W3, reps=1,
              name="raw dense 16-layer stack fwd+bwd")


PARTS = {"step": part_step, "flash": part_flash, "sdpa": part_sdpa,
         "ce": part_ce, "embed": part_embed, "raw": part_raw}

if __name__ == "__main__":
    names = sys.argv[1:] or list(PARTS)
    for nm in names:
        PARTS[nm]()
