"""tools.analyze — unified multi-pass static analysis for paddle_tpu.

Usage (CLI):   python -m tools.analyze [root] [--json] [--pass <id>]
Usage (API):   from tools.analyze import analyze_tree
               report = analyze_tree("/path/to/repo")

See tools/analyze/core.py for the framework (shared AST index,
findings, suppressions, baseline) and tools/analyze/passes/ for the
eleven passes. The README's "Static analysis" section documents the
pass catalogue and the suppression/baseline policy.
"""
from tools.analyze.core import (Baseline, Finding, Report, build_index,
                                default_baseline_path, run)
from tools.analyze.passes import ALL_PASSES, BY_ID

__all__ = ["Baseline", "Finding", "Report", "ALL_PASSES", "BY_ID",
           "build_index", "run", "analyze_tree",
           "default_baseline_path"]


def analyze_tree(root, pass_ids=None, baseline_path=None,
                 use_baseline=True) -> Report:
    """Run the suite (or the `pass_ids` subset) over `root` and return
    a Report. `baseline_path=None` with use_baseline=True uses the
    checked-in tools/analyze/baseline.json."""
    if pass_ids:
        unknown = [p for p in pass_ids if p not in BY_ID]
        if unknown:
            raise ValueError(
                f"unknown pass id(s) {unknown}; known: "
                f"{sorted(BY_ID)}")
        passes = [BY_ID[p] for p in pass_ids]
    else:
        passes = ALL_PASSES
    baseline = None
    if use_baseline:
        baseline = Baseline.load(baseline_path
                                 or default_baseline_path())
    return run(root, passes, baseline, known_ids=set(BY_ID))
