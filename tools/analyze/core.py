"""Core of the unified static-analysis framework.

One parse of the corpus (`paddle_tpu/`, `tools/`, `bench.py`) into a
shared :class:`Index` — per-module AST with parent links and def/class
qualnames, raw source lines, and the inline-suppression table — then
every registered pass (tools/analyze/passes/) runs over the same index
and emits typed :class:`Finding`s.

Finding lifecycle:

  pass emits Finding
    -> suppressed?   `# lint: disable=<pass-id> -- justification` on
                     the finding's line removes it (a suppression with
                     NO justification is itself a finding)
    -> baselined?    an entry in tools/analyze/baseline.json keyed by
                     (pass, file, line) grandfathers it (green at
                     introduction; the baseline only ever shrinks)
    -> otherwise     it is NEW and the run exits non-zero.

Stale baseline entries and unused suppressions are reported as
warnings without failing, so the ratchet is visible but a mid-refactor
tree doesn't flap.
"""
from __future__ import annotations

import ast
import io
import json
import os
import re
import tokenize
from dataclasses import dataclass, field, replace

# directories/files that make up the analyzed corpus, relative to root
CORPUS_DIRS = ("paddle_tpu", "tools")
CORPUS_FILES = ("bench.py",)
SKIP_DIRS = {"__pycache__", ".git"}

# `# lint: disable=<id>[,<id>...] -- justification`  (the justification
# is REQUIRED: a suppression that doesn't say why is itself a finding)
_SUPPRESS_RE = re.compile(
    r"#\s*lint:\s*disable=([A-Za-z0-9_\-]+(?:\s*,\s*[A-Za-z0-9_\-]+)*)"
    r"(?:\s*--\s*(.*))?")


@dataclass(frozen=True)
class Finding:
    """One typed diagnostic: {pass, severity, file, line, qualname,
    message}.  `qualname` (the enclosing def/class) is filled in
    centrally by the runner from the finding's line — passes never need
    to compute it."""
    pass_id: str
    file: str               # path relative to the analyzed root
    line: int
    message: str
    severity: str = "error"
    qualname: str = ""      # enclosing def/class ("" = module level)

    def key(self):
        return (self.pass_id, self.file, self.line)

    def to_json(self, suppressed=False):
        return {"pass": self.pass_id, "severity": self.severity,
                "file": self.file, "line": self.line,
                "qualname": self.qualname, "message": self.message,
                "suppressed": suppressed}

    def render(self):
        where = f" ({self.qualname})" if self.qualname else ""
        return (f"[{self.pass_id}] {self.file}:{self.line}{where}: "
                f"{self.message}")


@dataclass
class Module:
    """One parsed corpus file."""
    path: str                      # absolute
    rel: str                       # relative to Index.root
    source: str
    lines: list = field(default_factory=list)          # 1-based via [no-1]
    tree: ast.Module | None = None
    parse_error: str | None = None
    # line -> set of suppressed pass ids (only well-formed suppressions)
    suppressions: dict = field(default_factory=dict)
    # (line, raw_comment) for suppressions missing their justification
    bad_suppressions: list = field(default_factory=list)

    def qualname(self, node) -> str:
        """Dotted def/class qualname ("Trainer.step", "Engine._tick.run")
        computed from parent links at index time."""
        return getattr(node, "_pt_qualname", getattr(node, "name", "?"))

    def qualname_at(self, line: int) -> str:
        """Innermost def/class qualname containing `line` ("" when the
        line sits at module level)."""
        spans = getattr(self, "_qual_spans", None)
        if spans is None:
            spans = []
            if self.tree is not None:
                for node in ast.walk(self.tree):
                    if isinstance(node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef,
                                         ast.ClassDef)):
                        end = getattr(node, "end_lineno", node.lineno)
                        spans.append((node.lineno, end,
                                      self.qualname(node)))
            self._qual_spans = spans
        best = ""
        best_start = -1
        for start, end, qn in spans:
            if start <= line <= end and start > best_start:
                best, best_start = qn, start
        return best


class Index:
    """The shared AST index every pass runs over."""

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        self.modules: list[Module] = []
        self.by_rel: dict[str, Module] = {}

    def add(self, mod: Module):
        self.modules.append(mod)
        self.by_rel[mod.rel] = mod

    def under(self, prefix: str):
        """Modules whose relpath sits under `prefix` (a corpus subdir)."""
        pre = prefix.rstrip(os.sep) + os.sep
        for m in self.modules:
            if m.rel.startswith(pre) or m.rel == prefix:
                yield m


def _iter_corpus(root, subdirs=CORPUS_DIRS, files=CORPUS_FILES):
    for sub in subdirs:
        top = os.path.join(root, sub)
        if not os.path.isdir(top):
            continue
        for dirpath, dirnames, filenames in os.walk(top):
            dirnames[:] = sorted(d for d in dirnames if d not in SKIP_DIRS)
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    yield os.path.join(dirpath, fn)
    for fn in files:
        path = os.path.join(root, fn)
        if os.path.isfile(path):
            yield path


def _link_parents(tree):
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            child.parent = node


def _assign_qualnames(tree):
    """Set ._pt_qualname on every def/class: enclosing def/class names
    joined with '.' (no `<locals>` noise — this feeds config matching
    like "Trainer.step", not introspection)."""

    def visit(node, prefix):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                qn = f"{prefix}.{child.name}" if prefix else child.name
                child._pt_qualname = qn
                visit(child, qn)
            else:
                visit(child, prefix)

    visit(tree, "")


def _iter_comments(mod: Module):
    """(lineno, comment_text) for every real COMMENT token — a
    suppression spelled inside a string literal or docstring is prose,
    not a directive, and must not count."""
    try:
        toks = tokenize.generate_tokens(io.StringIO(mod.source).readline)
        for tok in toks:
            if tok.type == tokenize.COMMENT:
                yield tok.start[0], tok.string
    except (tokenize.TokenError, IndentationError, SyntaxError):
        # untokenizable file: fall back to raw lines so suppressions
        # keep working on files the AST passes already skip
        for no, line in enumerate(mod.lines, 1):
            if "#" in line and "lint:" in line:
                yield no, line[line.index("#"):]


def _parse_suppressions(mod: Module):
    if "lint:" not in mod.source:      # cheap gate: most files have no
        return                         # directives; skip tokenization
    for no, comment in _iter_comments(mod):
        if "lint:" not in comment:
            continue
        m = _SUPPRESS_RE.search(comment)
        if not m:
            continue
        ids = {p.strip() for p in m.group(1).split(",") if p.strip()}
        just = (m.group(2) or "").strip()
        if not just:
            mod.bad_suppressions.append((no, comment.strip()))
            continue
        mod.suppressions.setdefault(no, set()).update(ids)


def build_index(root: str, subdirs=CORPUS_DIRS,
                files=CORPUS_FILES) -> Index:
    """Parse the corpus once. Files that fail to parse keep their raw
    lines (line-based passes still see them) with tree=None.
    `subdirs`/`files` narrow the corpus — the legacy `scan(root)` shims
    index only paddle_tpu/ instead of paying for the full tree."""
    index = Index(root)
    for path in _iter_corpus(index.root, subdirs, files):
        rel = os.path.relpath(path, index.root)
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
        except OSError as e:
            index.add(Module(path=path, rel=rel, source="",
                             parse_error=f"unreadable: {e}"))
            continue
        mod = Module(path=path, rel=rel, source=source,
                     lines=source.splitlines())
        try:
            mod.tree = ast.parse(source, filename=rel)
            _link_parents(mod.tree)
            _assign_qualnames(mod.tree)
        except SyntaxError as e:
            mod.parse_error = f"syntax error: {e}"
        _parse_suppressions(mod)
        index.add(mod)
    return index


# -- baseline ----------------------------------------------------------------

class Baseline:
    """Checked-in grandfather list: findings present when their pass was
    introduced. Keyed (pass, file, line); every entry carries a
    justification so the file documents WHY each one is tolerated."""

    def __init__(self, entries=None, path=None):
        self.path = path
        self.entries = list(entries or [])
        self._keys = {(e["pass"], e["file"], int(e["line"]))
                      for e in self.entries}

    @classmethod
    def load(cls, path):
        if path is None or not os.path.isfile(path):
            return cls(path=path)
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
        return cls(doc.get("entries", []), path=path)

    def match(self, finding: Finding) -> bool:
        return finding.key() in self._keys

    def stale(self, findings, ran_pass_ids=None) -> list:
        """Entries whose finding no longer occurs. With `ran_pass_ids`
        (a `--pass`-filtered run), entries for passes that did not run
        are unknowable, not stale."""
        hit = {f.key() for f in findings}
        return [e for e in self.entries
                if (ran_pass_ids is None or e["pass"] in ran_pass_ids)
                and (e["pass"], e["file"], int(e["line"])) not in hit]

    @staticmethod
    def dump(findings, path, prior=None, ran_pass_ids=None):
        """Rewrite the baseline from `findings`. Surviving entries keep
        the justification they carry in `prior` (a Baseline); only
        genuinely new entries get the TODO placeholder. With
        `ran_pass_ids` set (a `--pass`-filtered run), entries for
        passes that did NOT run are retained verbatim instead of being
        silently dropped."""
        prior = prior or Baseline()
        carried = {(e["pass"], e["file"], int(e["line"])):
                   e.get("justification")
                   for e in prior.entries}
        entries = [{"pass": f.pass_id, "file": f.file, "line": f.line,
                    "message": f.message,
                    "justification": carried.get(f.key())
                    or "TODO: justify or fix"}
                   for f in findings]
        if ran_pass_ids is not None:
            have = {f.key() for f in findings}
            entries += [
                e for e in prior.entries
                if e["pass"] not in ran_pass_ids
                and (e["pass"], e["file"], int(e["line"])) not in have]
        entries.sort(key=lambda e: (e["pass"], e["file"], e["line"]))
        with open(path, "w", encoding="utf-8") as f:
            json.dump({"version": 1, "entries": entries}, f, indent=2)
            f.write("\n")


# -- runner ------------------------------------------------------------------

@dataclass
class Report:
    root: str
    pass_ids: list
    new: list              # non-baselined, non-suppressed findings
    baselined: list
    suppressed: list
    warnings: list         # stale baseline entries, unused suppressions
    notes: dict = field(default_factory=dict)   # pass id -> table lines

    @property
    def exit_code(self):
        return 1 if self.new else 0

    def to_json(self):
        """Schema-stable (version 2) document for CI consumption.
        Version 2 (ISSUE 15): findings carry `qualname` and a
        `suppressed` flag (suppressed findings are INCLUDED, flagged
        true, so CI can audit them; only suppressed=false findings
        affect the exit code), plus per-pass `notes` tables (e.g.
        lock-order's canonical acquisition order)."""
        return {
            "version": 2,
            "root": self.root,
            "passes": list(self.pass_ids),
            "findings": [f.to_json() for f in self.new]
            + [f.to_json(suppressed=True) for f in self.suppressed],
            "counts": {"new": len(self.new),
                       "baselined": len(self.baselined),
                       "suppressed": len(self.suppressed)},
            "warnings": list(self.warnings),
            "notes": {k: list(v) for k, v in self.notes.items()},
        }


def default_baseline_path():
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "baseline.json")


def run(root, passes, baseline: Baseline | None = None,
        known_ids=None) -> Report:
    """Build the index once, run `passes` (modules exposing PASS_ID and
    run(index)), fold in framework findings (malformed suppressions),
    then apply suppressions and the baseline. `known_ids` is the FULL
    pass registry (defaults to the ids of `passes`): on a filtered
    `--pass` run, a suppression for a non-running pass is still a known
    pass — neither unknown nor unused."""
    index = build_index(root)
    ran_ids = {p.PASS_ID for p in passes}
    known_ids = set(known_ids) if known_ids else ran_ids

    findings = []
    for p in passes:
        findings.extend(p.run(index))

    # framework-level: a suppression without a justification is a
    # finding in its own right (and is itself unsuppressible)
    for mod in index.modules:
        for no, raw in mod.bad_suppressions:
            findings.append(Finding(
                "suppression", mod.rel, no,
                f"suppression comment has no justification: {raw!r} — "
                "write `# lint: disable=<pass-id> -- <why>`"))

    # central qualname enrichment (AFTER the framework findings so
    # they carry one too): the finding's line names its enclosing
    # def/class, no pass has to carry that plumbing
    enriched = []
    for f in findings:
        if not f.qualname:
            mod = index.by_rel.get(f.file)
            if mod is not None:
                qn = mod.qualname_at(f.line)
                if qn:
                    f = replace(f, qualname=qn)
        enriched.append(f)
    findings = enriched

    notes = {}
    for p in passes:
        summarize = getattr(p, "summarize", None)
        if summarize:
            lines = list(summarize(index))
            if lines:
                notes[p.PASS_ID] = lines

    new, suppressed = [], []
    used = set()                      # (rel, line, pass_id) consumed
    for f in findings:
        mod = index.by_rel.get(f.file)
        ids = mod.suppressions.get(f.line, set()) if mod else set()
        if f.pass_id != "suppression" and f.pass_id in ids:
            suppressed.append(f)
            used.add((f.file, f.line, f.pass_id))
        else:
            new.append(f)

    warnings = []
    for mod in index.modules:
        if mod.parse_error:
            warnings.append(f"{mod.rel}: skipped AST passes "
                            f"({mod.parse_error})")
        for no, ids in sorted(mod.suppressions.items()):
            for pid in sorted(ids):
                if pid not in known_ids and pid != "suppression":
                    warnings.append(
                        f"{mod.rel}:{no}: suppression names unknown "
                        f"pass {pid!r}")
                elif pid in ran_ids and (mod.rel, no, pid) not in used:
                    warnings.append(
                        f"{mod.rel}:{no}: unused suppression for "
                        f"{pid!r} (nothing to suppress — remove it)")

    baseline = baseline or Baseline()
    kept, grandfathered = [], []
    for f in new:
        (grandfathered if baseline.match(f) else kept).append(f)
    for e in baseline.stale(new, ran_pass_ids=ran_ids):
        warnings.append(
            f"stale baseline entry ({e['pass']} {e['file']}:{e['line']})"
            " — the finding is gone; ratchet by deleting the entry")

    kept.sort(key=lambda f: (f.file, f.line, f.pass_id))
    return Report(root=index.root, pass_ids=[p.PASS_ID for p in passes],
                  new=kept, baselined=grandfathered,
                  suppressed=suppressed, warnings=warnings, notes=notes)
