"""CLI runner: `python -m tools.analyze [root] [--json] [--pass <id>]`.

Exit-code contract (CI consumes this — keep it stable):

  0  zero NEW findings: everything emitted was either suppressed
     inline (`# lint: disable=<id> -- why`) or grandfathered in
     tools/analyze/baseline.json.  Warnings (stale baseline entries,
     unused suppressions, unparseable files) NEVER affect the exit
     code — they print to stdout and are advisory.
  1  at least one new finding.  Human mode prints each to stderr as
     `[pass] file:line (qualname): message`; --json mode prints the
     document to stdout and still exits 1.
  2  usage error (unknown --pass id, bad arguments).

--json emits the schema-stable (version 2) document from
Report.to_json(): each finding carries {pass, severity, file, line,
qualname, message, suppressed}.  Suppressed findings are included with
suppressed=true for auditability; only suppressed=false findings drive
the exit code.  `notes` holds per-pass tables (lock-order's canonical
acquisition order); `counts` and `warnings` round out the document.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

# `python tools/analyze/__main__.py` (not -m): make tools.* importable
_REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from tools.analyze import (ALL_PASSES, BY_ID, Baseline,  # noqa: E402
                           analyze_tree, default_baseline_path)


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m tools.analyze",
        description="multi-pass static analysis for the paddle_tpu "
                    "corpus (paddle_tpu/, tools/, bench.py)")
    ap.add_argument("root", nargs="?", default=None,
                    help="tree to analyze (default: this repo)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the version-2 JSON document (findings "
                         "with qualname + suppressed flag, notes)")
    ap.add_argument("--pass", dest="passes", action="append",
                    metavar="ID", default=None,
                    help="run only this pass (repeatable)")
    ap.add_argument("--list-passes", action="store_true",
                    help="print the pass catalogue and exit")
    ap.add_argument("--tables", action="store_true",
                    help="print per-pass summary tables (e.g. the "
                         "lock-order canonical acquisition order)")
    ap.add_argument("--baseline", default=None, metavar="PATH",
                    help="baseline file (default: "
                         "tools/analyze/baseline.json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline (every finding is new)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="re-write the baseline from the current "
                         "findings (ratchet helper; justifications "
                         "must then be filled in by hand)")
    args = ap.parse_args(argv)

    if args.list_passes:
        for p in ALL_PASSES:
            print(f"{p.PASS_ID:18s} {p.DESCRIPTION}")
        return 0

    root = args.root or os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    if args.passes:
        unknown = [p for p in args.passes if p not in BY_ID]
        if unknown:
            print(f"unknown pass id(s): {', '.join(unknown)} "
                  f"(known: {', '.join(sorted(BY_ID))})",
                  file=sys.stderr)
            return 2

    report = analyze_tree(
        root, pass_ids=args.passes,
        baseline_path=args.baseline,
        use_baseline=not args.no_baseline)

    if args.write_baseline:
        path = args.baseline or default_baseline_path()
        Baseline.dump(report.new + report.baselined, path,
                      prior=Baseline.load(path),
                      ran_pass_ids=set(args.passes) if args.passes
                      else set(BY_ID))
        print(f"tools.analyze: wrote {len(report.new) + len(report.baselined)} "
              f"baseline entr(ies) to {path}")
        return 0

    if args.as_json:
        print(json.dumps(report.to_json(), indent=2))
        return report.exit_code

    for w in report.warnings:
        print(f"tools.analyze: warning: {w}")
    if args.tables:
        for pid, lines in sorted(report.notes.items()):
            print(f"-- {pid} --")
            for line in lines:
                print(f"  {line}")
    if report.new:
        print(f"tools.analyze: {len(report.new)} new finding(s) "
              f"({len(report.baselined)} baselined, "
              f"{len(report.suppressed)} suppressed):", file=sys.stderr)
        for f in report.new:
            print(f"  {f.render()}", file=sys.stderr)
        return 1
    print(f"tools.analyze: clean — {len(ALL_PASSES if not args.passes else args.passes)} "
          f"pass(es), 0 new finding(s) "
          f"({len(report.baselined)} baselined, "
          f"{len(report.suppressed)} suppressed)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
