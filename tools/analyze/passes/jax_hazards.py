"""Pass `jax-hazards` — donation misuse and retrace bait.

Two families, both invisible until they corrupt results or melt the
recompile counters PR 9 labels by shape:

DONATION.  `jax.jit(f, donate_argnums=(0,))` invalidates the caller's
buffer at position 0 the moment the call runs.  For every jit wrapper
whose donate positions are LITERAL (dynamic `donate_argnums=donate` is
untrackable and skipped), each call site is checked for the two
use-after-donate shapes:

  * the donated variable is read again later in the same function
    (unless the call rebinds it — `state = step(state, batch)` is the
    sanctioned idiom);
  * the call sits in a loop and the donated variable is never rebound
    inside that loop, so iteration 2 passes a deleted buffer.

RETRACE.  `jax.jit` caches per wrapper object, so a wrapper built per
call never hits its cache:

  * `jax.jit(f)(x)` immediately invoked inside a function;
  * a wrapper bound to a local and only ever called there (returning
    it or storing it to `self.*`/a container is the factory/cache
    pattern and fine);
  * calls that yield a fresh Python value every invocation
    (`time.time`, `random.*`, `uuid4`, ...) inside a jit-traced body —
    the value is baked in as a constant at trace time: silently stale,
    and different on every retrace.
"""
from __future__ import annotations

import ast

from tools.analyze.core import Finding
from tools.analyze.passes._util import (call_snippet, dotted, stmt_of,
                                        walk_no_defs)

PASS_ID = "jax-hazards"
DESCRIPTION = ("use-after-donate at donate_argnums call sites; "
               "per-call jit wrappers and trace-time-constant calls "
               "that force retraces")

_DEFS = (ast.FunctionDef, ast.AsyncFunctionDef)
_LOOPS = (ast.For, ast.AsyncFor, ast.While)

# callables whose result varies per call: traced to a stale constant
_VARYING = {"time.time", "time.monotonic", "time.perf_counter",
            "time.time_ns", "datetime.now", "datetime.utcnow",
            "datetime.datetime.now", "datetime.datetime.utcnow",
            "random.random", "random.randint", "random.uniform",
            "random.randrange", "random.choice", "uuid.uuid4",
            "uuid4", "os.urandom"}
_VARYING_PREFIXES = ("np.random.", "numpy.random.")


def _is_jit_func(expr):
    """`jax.jit` / `jit` / `pjit` (or a functools.partial of one)."""
    d = dotted(expr)
    if d and (d in ("jit", "pjit") or d.endswith(".jit")
              or d.endswith(".pjit")):
        return True
    if isinstance(expr, ast.Call):
        pd = dotted(expr.func)
        if pd in ("partial", "functools.partial") and expr.args:
            return _is_jit_func(expr.args[0])
    return False


def _is_jit_call(node):
    return isinstance(node, ast.Call) and _is_jit_func(node.func)


def _literal_donate(call):
    """The literal donate positions of a jit call, or None."""
    for kw in call.keywords:
        if kw.arg not in ("donate_argnums", "donate_argnames"):
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, int):
            return (v.value,)
        if isinstance(v, (ast.Tuple, ast.List)) and all(
                isinstance(e, ast.Constant)
                and isinstance(e.value, int) for e in v.elts):
            return tuple(e.value for e in v.elts)
        return None
    return None


def _enclosing_loop_in(node, fn):
    """Nearest enclosing loop WITHIN `fn` — stops at any function
    boundary (the equivalent of cv_discipline's _in_while rule)."""
    cur = getattr(node, "parent", None)
    while cur is not None and cur is not fn:
        if isinstance(cur, _LOOPS):
            return cur
        if isinstance(cur, _DEFS + (ast.Lambda,)):
            return None
        cur = getattr(cur, "parent", None)
    return None


def _assigned_names(stmt):
    """Names bound by an assignment statement (targets only)."""
    out = set()
    if isinstance(stmt, ast.Assign):
        targets = stmt.targets
    elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
        targets = [stmt.target]
    else:
        return out
    for t in targets:
        for n in ast.walk(t):
            if isinstance(n, ast.Name):
                out.add(n.id)
    return out


def _jit_wrappers(root, walk=walk_no_defs):
    """{name: donate positions} for `name = jax.jit(..,
    donate_argnums=<literal>)` bindings in `root`'s own body (a
    function via walk_no_defs, or the module body)."""
    out = {}
    for node in walk(root):
        if not isinstance(node, ast.Assign) or not _is_jit_call(node.value):
            continue
        donate = _literal_donate(node.value)
        if donate is None:
            continue
        for t in node.targets:
            if isinstance(t, ast.Name):
                out[t.id] = donate
    return out


def _module_wrappers(mod):
    """Module-level donate wrappers (`_step = jax.jit(...)` at top
    level) — callable from every function in the module.  Wrappers
    cached on `self.*` attrs are out of model (callee types would be a
    guess)."""
    return _jit_wrappers(mod.tree, walk=lambda t: t.body)


def _check_donation(mod, fn, module_wrappers):
    # ANY local binding (param, assignment, loop target) shadows a
    # module-level wrapper of the same name — a local `_step =
    # jax.jit(g)` without donation must not inherit the module
    # wrapper's donate positions
    shadowed = set()
    for n in walk_no_defs(fn):
        if isinstance(n, ast.Name) and isinstance(n.ctx,
                                                  (ast.Store, ast.Del)):
            shadowed.add(n.id)
        elif isinstance(n, ast.arg):
            shadowed.add(n.arg)
    wrappers = {k: v for k, v in module_wrappers.items()
                if k not in shadowed}
    wrappers.update(_jit_wrappers(fn))
    if not wrappers:
        return
    for node in walk_no_defs(fn):
        if not isinstance(node, ast.Call) \
                or not isinstance(node.func, ast.Name) \
                or node.func.id not in wrappers:
            continue
        donate = wrappers[node.func.id]
        stmt = stmt_of(node)
        rebound = _assigned_names(stmt) if stmt else set()
        for pos in donate:
            if pos >= len(node.args):
                continue
            arg = node.args[pos]
            name = arg.id if isinstance(arg, ast.Name) else None
            if name is None or name in rebound:
                continue        # `x = f(x)` rebinding is the idiom
            # (a) later read in the same function — unless some Store
            # rebinds the name between the call and the read (a fresh
            # value, not the donated buffer)
            stores = [n.lineno for n in walk_no_defs(fn)
                      if isinstance(n, ast.Name) and n.id == name
                      and isinstance(n.ctx, ast.Store)]
            for later in walk_no_defs(fn):
                if isinstance(later, ast.Name) and later.id == name \
                        and isinstance(later.ctx, ast.Load) \
                        and later.lineno > node.lineno \
                        and later is not arg \
                        and not any(node.lineno < s <= later.lineno
                                    for s in stores):
                    yield Finding(
                        PASS_ID, mod.rel, later.lineno,
                        f"`{name}` read after being donated to "
                        f"`{node.func.id}` (donate_argnums position "
                        f"{pos}, call at line {node.lineno}) — the "
                        "buffer is deleted by donation; rebind the "
                        "result or drop donation")
                    break
            # (b) donated in a loop without rebinding — the loop must
            # be within THIS function (a nested def's parameters are
            # fresh per call; an outer function's loop does not reuse
            # the callee's donated arg)
            loop = _enclosing_loop_in(node, fn)
            if loop is not None:
                rebinds = set()
                for s in ast.walk(loop):
                    rebinds |= _assigned_names(s) if isinstance(
                        s, (ast.Assign, ast.AnnAssign, ast.AugAssign)) \
                        else set()
                    if isinstance(s, (ast.For, ast.AsyncFor)):
                        rebinds |= {n.id for n in ast.walk(s.target)
                                    if isinstance(n, ast.Name)}
                if name not in rebinds:
                    yield Finding(
                        PASS_ID, mod.rel, node.lineno,
                        f"`{name}` donated to `{node.func.id}` inside "
                        "a loop without being rebound — iteration 2 "
                        "passes an already-deleted buffer")


def _escapes(fn, name, binding_stmt):
    """Does local `name` escape `fn`?  Any Load of the name OTHER than
    as the function of a call counts: returned, yielded, aliased,
    stored to an attr/subscript/container, or passed as an argument.
    `f(x)` alone does not escape — that is exactly the call-only shape
    being hunted."""
    for n in walk_no_defs(fn):
        if not (isinstance(n, ast.Name) and n.id == name
                and isinstance(n.ctx, ast.Load)):
            continue
        if stmt_of(n) is binding_stmt:
            continue
        p = getattr(n, "parent", None)
        if isinstance(p, ast.Call) and p.func is n:
            continue
        return True
    return False


def _check_retrace_wrappers(mod, fn):
    """jit wrappers built per call inside `fn`."""
    for node in walk_no_defs(fn):
        if not _is_jit_call(node):
            continue
        parent = getattr(node, "parent", None)
        # (a) jax.jit(f)(x): invoked the moment it is built
        if isinstance(parent, ast.Call) and parent.func is node:
            yield Finding(
                PASS_ID, mod.rel, node.lineno,
                f"{call_snippet(parent)}: jit wrapper built and "
                "invoked in one expression — a fresh wrapper per call "
                "never hits the jit cache and retraces every time; "
                "build it once (module level, __init__, or an lru "
                "cache)")
            continue
        # (b) bound to a local that never escapes: called-only locals
        if isinstance(parent, ast.Assign) \
                and len(parent.targets) == 1 \
                and isinstance(parent.targets[0], ast.Name):
            name = parent.targets[0].id
            if not _escapes(fn, name, parent):
                called = any(
                    isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Name)
                    and n.func.id == name
                    for n in walk_no_defs(fn))
                if called:
                    yield Finding(
                        PASS_ID, mod.rel, node.lineno,
                        f"jit wrapper `{name}` is built and called "
                        f"inside `{fn.name}` but never cached/returned "
                        "— every call to the enclosing function "
                        "retraces; hoist or cache the wrapper")


def _jitted_defs(mod):
    """FunctionDefs that are jit-traced: decorated with jit, or passed
    by name to a jax.jit(...) call in the module."""
    names = set()
    by_name = {}
    for node in ast.walk(mod.tree):
        if isinstance(node, _DEFS):
            by_name.setdefault(node.name, node)
            if any(_is_jit_func(dec) or _is_jit_call(dec)
                   for dec in node.decorator_list):
                names.add(node.name)
        elif _is_jit_call(node) and node.args:
            a0 = node.args[0]
            if isinstance(a0, ast.Name):
                names.add(a0.id)
    return [by_name[n] for n in sorted(names) if n in by_name]


def _check_varying_in_traced(mod):
    for fn in _jitted_defs(mod):
        for node in walk_no_defs(fn):
            if not isinstance(node, ast.Call):
                continue
            d = dotted(node.func)
            if not d:
                continue
            if d in _VARYING or d.startswith(_VARYING_PREFIXES):
                yield Finding(
                    PASS_ID, mod.rel, node.lineno,
                    f"`{d}()` inside jit-traced `{fn.name}` — the "
                    "value is frozen at trace time (stale on every "
                    "cached call, different on every retrace); pass "
                    "it in as an argument instead")


def run(index):
    for mod in index.modules:
        if mod.tree is None:
            continue
        module_wrappers = _module_wrappers(mod)
        for node in ast.walk(mod.tree):
            if isinstance(node, _DEFS):
                yield from _check_donation(mod, node, module_wrappers)
                yield from _check_retrace_wrappers(mod, node)
        yield from _check_varying_in_traced(mod)
