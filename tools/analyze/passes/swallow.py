"""Pass `silent-swallow` — broad exception handlers may not eat errors.

`except Exception: pass` (or bare `except:` / `except BaseException:`,
or a lone `continue`) inside a background writer, ticker loop, or any
other body turns real failures into silence: the thread keeps running
(or dies later, elsewhere), the operator sees nothing, and the bug
report arrives as "training hung". Every such handler must either
re-raise, record the failure somewhere visible (metric, log, stderr),
or carry an inline justification:

    except Exception:   # lint: disable=silent-swallow -- <why this is safe>
        pass

Handlers that DO something (assign a fallback, return a default, log,
count) are not flagged — only bodies that are nothing but
`pass`/`continue`/`...`.
"""
from __future__ import annotations

import ast

from tools.analyze.core import Finding

PASS_ID = "silent-swallow"
DESCRIPTION = ("`except Exception: pass` must re-raise, record, or "
               "carry a justification")

_BROAD = {"Exception", "BaseException"}


def _is_broad(handler_type):
    if handler_type is None:
        return True                                 # bare except:
    names = []
    if isinstance(handler_type, ast.Tuple):
        names = handler_type.elts
    else:
        names = [handler_type]
    for n in names:
        if isinstance(n, ast.Name) and n.id in _BROAD:
            return True
        if isinstance(n, ast.Attribute) and n.attr in _BROAD:
            return True
    return False


def _is_silent(body):
    """True when the handler body does literally nothing: only
    pass/continue/`...` statements."""
    for stmt in body:
        if isinstance(stmt, (ast.Pass, ast.Continue)):
            continue
        if isinstance(stmt, ast.Expr) \
                and isinstance(stmt.value, ast.Constant) \
                and stmt.value.value is Ellipsis:
            continue
        return False
    return True


def run(index):
    for mod in index.modules:
        if mod.tree is None:
            continue
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not _is_broad(node.type) or not _is_silent(node.body):
                continue
            kind = ("bare except" if node.type is None
                    else f"except {ast.unparse(node.type)}")
            what = ("continue" if any(isinstance(s, ast.Continue)
                                      for s in node.body) else "pass")
            yield Finding(
                PASS_ID, mod.rel, node.lineno,
                f"`{kind}: {what}` swallows failures silently — "
                "re-raise, record to a metric/log, or add "
                "`# lint: disable=silent-swallow -- <why>`")
