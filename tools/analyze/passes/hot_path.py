"""Pass `hot-path-sync` — no host syncs inside traced/jit bodies.

The dispatch hot path (PR 4 made `Trainer.step` zero-`device_put`;
PR 6's paged tick is one fused jit call) dies by a thousand implicit
host syncs: `.item()`, `float()/int()/bool()` on array values,
`np.asarray`, `jax.device_get`, `.block_until_ready()` and `print`
all force the dispatch thread to wait on the device (or fail outright
under tracing). This pass flags them inside

  - functions decorated with `@jax.jit` / `@partial(jax.jit, ...)` /
    `@pl.pallas_call(...)`,
  - functions *wrapped* at a distance: any name referenced in the
    first argument of a call whose callee name contains "jit"
    (`jax.jit(step, ...)`, `self._jit_step(step)`) or is
    `pallas_call(kernel, ...)` — lambdas in that argument count too,
  - the configured known hot bodies (KNOWN_HOT qualnames).

`int()/float()/bool()` on constants or on shape/ndim/dtype expressions
are static under tracing and exempt; `jax.debug.print` is the
sanctioned in-graph print and is not flagged.
"""
from __future__ import annotations

import ast

from tools.analyze.core import Finding
from tools.analyze.passes._util import dotted

PASS_ID = "hot-path-sync"
DESCRIPTION = ("host syncs (.item/float/np.asarray/device_get/print) "
               "inside jit-traced or known-hot functions")

# qualnames treated as hot even without a visible jit wrapper: the
# trainer's per-step dispatch body (PR 4's zero-device_put contract)
KNOWN_HOT = {"Trainer.step"}

_NUMPY_MATERIALIZERS = {"asarray", "array"}
_CAST_BUILTINS = {"float", "int", "bool"}
_DEFS = (ast.FunctionDef, ast.AsyncFunctionDef)


def _numpy_aliases(tree):
    """Names the module binds to the numpy module ('np', 'numpy')."""
    aliases = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "numpy":
                    aliases.add(a.asname or "numpy")
    return aliases


def _callee_is_jitlike(call):
    """True when `call` wraps its first argument in a traced context:
    the callee's last name component contains 'jit' (jax.jit, jit,
    self._jit_step) or is 'pallas_call'."""
    name = dotted(call.func)
    if name is None and isinstance(call.func, ast.Attribute):
        name = call.func.attr
    if not name:
        return False
    last = name.rsplit(".", 1)[-1].lower()
    return "jit" in last or last == "pallas_call"


def _decorator_is_jitlike(dec):
    """@jax.jit / @jit / @partial(jax.jit, ...) / @pl.pallas_call(...)."""
    exprs = [dec]
    if isinstance(dec, ast.Call):
        exprs = [dec.func] + list(dec.args)
    for e in exprs:
        name = dotted(e)
        if not name:
            continue
        last = name.rsplit(".", 1)[-1].lower()
        if "jit" in last or last == "pallas_call":
            return True
    return False


def _local_defs(tree):
    """name -> [def nodes] for every function def in the module (any
    nesting level); jit-wrap references resolve by name module-wide,
    which is the right granularity for `jax.jit(run, ...)` closures."""
    defs = {}
    for node in ast.walk(tree):
        if isinstance(node, _DEFS):
            defs.setdefault(node.name, []).append(node)
    return defs


def _traced_defs(mod):
    """The set of def/lambda nodes whose bodies execute under trace (or
    are configured hot), each with the reason it was selected."""
    tree = mod.tree
    defs_by_name = _local_defs(tree)
    traced = {}

    def mark(node, reason):
        traced.setdefault(node, reason)

    for node in ast.walk(tree):
        if isinstance(node, _DEFS):
            if any(_decorator_is_jitlike(d) for d in node.decorator_list):
                mark(node, f"`{node.name}` is jit/pallas-decorated")
            qn = mod.qualname(node)
            if qn in KNOWN_HOT:
                mark(node, f"`{qn}` is a known hot body")
        elif isinstance(node, ast.Call) and node.args \
                and _callee_is_jitlike(node):
            wrapper = dotted(node.func) or "jit"
            for ref in ast.walk(node.args[0]):
                if isinstance(ref, ast.Lambda):
                    mark(ref, f"lambda passed to {wrapper}(...)")
                elif isinstance(ref, ast.Name):
                    for d in defs_by_name.get(ref.id, ()):
                        mark(d, f"`{d.name}` is wrapped by "
                                f"{wrapper}(...)")
    return traced


def _is_static_cast_arg(arg):
    """float/int/bool on constants or shape/ndim/dtype/len expressions
    is resolved at trace time — not a device sync."""
    if isinstance(arg, ast.Constant):
        return True
    for n in ast.walk(arg):
        if isinstance(n, ast.Attribute) and n.attr in (
                "shape", "ndim", "dtype", "itemsize"):
            return True
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Name) \
                and n.func.id == "len":
            return True
    return False


def _scan_body(mod, fn_node, reason, np_aliases, seen):
    for node in walk_no_defs_body(fn_node):
        if not isinstance(node, ast.Call):
            continue
        key = (node.lineno, node.col_offset)
        if key in seen:
            continue
        msg = None
        f = node.func
        if isinstance(f, ast.Attribute):
            base = dotted(f.value)
            if f.attr == "item" and not node.args:
                msg = ".item() forces a blocking device->host sync"
            elif f.attr == "block_until_ready":
                msg = ".block_until_ready() is a host sync"
            elif f.attr == "device_get":
                msg = "jax.device_get pulls values to host"
            elif f.attr in _NUMPY_MATERIALIZERS and base in np_aliases:
                msg = (f"{base}.{f.attr}(...) materializes on host "
                       "(TracerArrayConversionError under tracing, "
                       "a sync otherwise)")
        elif isinstance(f, ast.Name):
            if f.id == "print":
                msg = ("print() breaks async dispatch (use "
                       "jax.debug.print inside traced code)")
            elif f.id in _CAST_BUILTINS and node.args \
                    and not all(_is_static_cast_arg(a)
                                for a in node.args):
                msg = (f"{f.id}() on an array value forces a "
                       "device sync / concretization")
        if msg:
            seen.add(key)
            yield Finding(PASS_ID, mod.rel, node.lineno,
                          f"{msg} — {reason}")


def walk_no_defs_body(fn_node):
    """Walk a traced function's WHOLE subtree including nested defs:
    a def nested in a traced body is traced too (lax.scan bodies,
    closures), so unlike the thread pass we do descend."""
    yield from ast.walk(fn_node)


def run(index):
    for mod in index.modules:
        if mod.tree is None:
            continue
        np_aliases = _numpy_aliases(mod.tree)
        traced = _traced_defs(mod)
        seen = set()
        # deterministic order: by position in file
        for fn_node in sorted(traced, key=lambda n: (n.lineno,
                                                     n.col_offset)):
            yield from _scan_body(mod, fn_node, traced[fn_node],
                                  np_aliases, seen)
