"""Pass `metric-names` — every metric instrumentation site is catalogued.

Port of tools/check_metric_names.py: `observability/metrics.py` carries
METRICS, the closed catalogue of every metric name. An instrumentation
call (`inc`/`observe`/`set_gauge`) with an uncatalogued or non-literal
name would mint a metric invisible to operators reading the docs;
acquisition calls (`counter`/`gauge`/`histogram`) are checked only when
their first argument IS a literal (np.histogram/jnp.histogram share the
method name with array first arguments and must not false-positive).

The legacy `scan(root) -> (violations, seen, catalogue)` surface is
kept for tools/check_metric_names.py (now a shim) and its tests.
"""
from __future__ import annotations

import ast
import importlib.util
import os

from tools.analyze.core import Finding, build_index

PASS_ID = "metric-names"
DESCRIPTION = ("metric instrumentation names must be string literals "
               "from the observability/metrics.py METRICS catalogue")

# literal-REQUIRED instrumentation calls
INSTRUMENTS = {"inc", "observe", "set_gauge"}
# literal-checked-when-literal acquisition calls
ACQUIRERS = {"counter", "gauge", "histogram"}

# the registry implementation itself passes `name` variables around;
# same for the module-level helper shims in the package __init__.
# observability/requests.py (the request-tracing SLO instrumentation)
# is deliberately NOT here: its request.* literals are audited like
# any other call site (tests/test_metric_names_tool.py pins that).
ALLOWED = {
    os.path.join("paddle_tpu", "observability", "metrics.py"),
    os.path.join("paddle_tpu", "observability", "__init__.py"),
}


def _load_catalogue(root: str) -> dict:
    path = os.path.join(root, "paddle_tpu", "observability", "metrics.py")
    if not os.path.isfile(path):
        return {}                   # no catalogue: nothing to audit
    spec = importlib.util.spec_from_file_location("_metrics_catalogue",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)        # stdlib-only module (no jax)
    return dict(getattr(mod, "METRICS", {}))


def _literal_of(node):
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _scan_index(index):
    """(violations, seen, catalogue); violations are (rel, lineno,
    call, problem)."""
    catalogue = _load_catalogue(index.root)
    violations = []
    seen = set()
    for mod in index.under("paddle_tpu"):
        if mod.rel in ALLOWED or mod.tree is None:
            continue
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            func = node.func
            name = (func.attr if isinstance(func, ast.Attribute)
                    else func.id if isinstance(func, ast.Name)
                    else None)
            if name not in INSTRUMENTS and name not in ACQUIRERS:
                continue
            metric = _literal_of(node.args[0])
            call = f"{name}({ast.unparse(node.args[0])})"
            if metric is None:
                if name in INSTRUMENTS:
                    violations.append(
                        (mod.rel, node.lineno, call,
                         "metric name is not a string literal — "
                         "cannot be audited against the METRICS "
                         "catalogue"))
                continue
            seen.add(metric)
            if metric not in catalogue:
                violations.append(
                    (mod.rel, node.lineno, call,
                     f"metric {metric!r} is not in the METRICS "
                     "catalogue (observability/metrics.py) — "
                     "register it there"))
    return violations, seen, catalogue


def run(index):
    violations, _seen, _cat = _scan_index(index)
    for rel, no, call, why in violations:
        yield Finding(PASS_ID, rel, no, f"{call}: {why}")


def scan(root: str):
    """Legacy surface (tools/check_metric_names.py shim + its tests).
    Indexes only paddle_tpu/ — all this scanner ever looked at."""
    return _scan_index(build_index(root, subdirs=("paddle_tpu",),
                                   files=()))
