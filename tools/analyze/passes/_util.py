"""Small AST helpers shared by the analysis passes."""
from __future__ import annotations

import ast


def dotted(node) -> str | None:
    """'jax.lax.scan' for nested Attribute/Name chains, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def terminal(node) -> str | None:
    """The last identifier of an expression: `self._lock` -> '_lock',
    `lock` -> 'lock', anything else -> None."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def func_name(call: ast.Call) -> str | None:
    """Last component of the called name: `threading.Thread(...)` ->
    'Thread', `t.join()` -> 'join'."""
    return terminal(call.func)


def stmt_of(node):
    """The statement a node belongs to (walk up to an ast.stmt)."""
    while node is not None and not isinstance(node, ast.stmt):
        node = getattr(node, "parent", None)
    return node


def enclosing(node, kinds):
    """Nearest ancestor of one of `kinds` (a tuple of AST types)."""
    node = getattr(node, "parent", None)
    while node is not None:
        if isinstance(node, kinds):
            return node
        node = getattr(node, "parent", None)
    return None


def walk_no_defs(node):
    """Yield nodes in `node`'s subtree WITHOUT descending into nested
    function/lambda bodies (deferred execution is a different context).
    `node` itself is yielded."""
    yield node
    for child in ast.iter_child_nodes(node):
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
            continue
        yield from walk_no_defs(child)


def call_snippet(call: ast.Call, max_len=60) -> str:
    try:
        s = ast.unparse(call)
    except Exception:      # degraded label is fine: unparse is cosmetic
        s = "<call>"
    return s if len(s) <= max_len else s[:max_len - 3] + "..."
