"""Pass `jax-compat` — version-fragile jax spellings (line-based).

Port of tools/check_jax_compat.py: `from jax import shard_map` /
`jax.shard_map(...)` / `jax.lax.axis_size(...)` only exist on jax>=0.6
and broke collection on 0.4.37; the sanctioned spellings live in
paddle_tpu/core/jax_compat.py. Line-based (works on files the AST
passes skip), with the comment/string stripper that keeps a stray
triple-quote in a COMMENT from hiding the rest of the file.

The legacy `scan(root)` surface is kept for tools/check_jax_compat.py
(now a shim) and its tests.
"""
from __future__ import annotations

import os
import re

from tools.analyze.core import Finding, build_index

PASS_ID = "jax-compat"
DESCRIPTION = ("version-fragile jax imports (shard_map/axis_size) that "
               "break on jax 0.4.x — use paddle_tpu.core.jax_compat")

# (pattern, why). Docstrings/comments are excluded by the stripper;
# prose mentions inside docstrings are tolerated (they can't break an
# import).
FRAGILE = [
    (re.compile(r"^\s*from\s+jax\s+import\s+(?:\([^)]*\bshard_map\b"
                r"|.*\bshard_map\b)"),
     "`from jax import shard_map` needs jax>=0.6; import it from "
     "paddle_tpu.core.jax_compat instead"),
    (re.compile(r"\bjax\.shard_map\s*\("),
     "`jax.shard_map(...)` needs jax>=0.6; use "
     "paddle_tpu.core.jax_compat.shard_map"),
    (re.compile(r"^\s*from\s+jax\.experimental\.shard_map\s+import"),
     "import shard_map via paddle_tpu.core.jax_compat (handles the "
     "check_rep->check_vma rename), not jax.experimental directly"),
    (re.compile(r"\bjax\.lax\.axis_size\s*\("),
     "`jax.lax.axis_size` does not exist on jax 0.4.x; use "
     "paddle_tpu.core.jax_compat.axis_size"),
]

# the one module allowed to touch the real locations
ALLOWED = {os.path.join("paddle_tpu", "core", "jax_compat.py")}

_PKG = "paddle_tpu" + os.sep


def _strip(line: str, open_q: str | None):
    """One stateful pass per line: returns (code, new_open_q) with
    comment trails and ALL string-literal contents removed. `open_q` is
    the delimiter of a still-open triple-quoted string from earlier
    lines (None when outside). Tracking strings and comments together
    is what keeps a stray triple-quote inside a COMMENT from hiding the
    rest of the file from the scan."""
    out = []
    i = 0
    while i < len(line):
        if open_q:
            j = line.find(open_q, i)
            if j < 0:
                return "".join(out), open_q     # string spans the line
            i = j + len(open_q)
            open_q = None
            continue
        if line.startswith('"""', i) or line.startswith("'''", i):
            open_q = line[i:i + 3]
            i += 3
            continue
        ch = line[i]
        if ch in "\"'":
            j = line.find(ch, i + 1)
            if j < 0:               # unterminated/escaped: drop the rest
                return "".join(out), None
            i = j + 1
            continue
        if ch == "#":
            return "".join(out), None
        out.append(ch)
        i += 1
    return "".join(out), open_q


def _scan_module(mod):
    """Yield (lineno, line, why) for every fragile use in one module."""
    open_q = None
    for no, line in enumerate(mod.lines, 1):
        code, open_q = _strip(line, open_q)
        for pat, why in FRAGILE:
            if pat.search(code):
                yield no, line.rstrip(), why
                break


def _scan_index(index):
    for mod in index.under("paddle_tpu"):
        if mod.rel in ALLOWED:
            continue
        for no, line, why in _scan_module(mod):
            yield mod.rel, no, line, why


def run(index):
    for rel, no, line, why in _scan_index(index):
        yield Finding(PASS_ID, rel, no, f"{line.strip()} -> {why}")


def scan(root: str):
    """Legacy surface (tools/check_jax_compat.py shim + its tests):
    yields (relpath, lineno, line, why) for every fragile use. Indexes
    only paddle_tpu/ — all this scanner ever looked at."""
    return list(_scan_index(build_index(root, subdirs=("paddle_tpu",),
                                        files=())))
