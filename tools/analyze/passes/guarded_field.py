"""Pass `guarded-field` — fields that are USUALLY locked must ALWAYS be
locked on cross-thread paths.

For every class owning at least one lock, each mutable attr's owning
lock is inferred from its writes: if at least two non-`__init__` writes
happen under one specific lock and more writes are guarded by it than
not, that lock owns the attr (majority vote — the bug being hunted IS
the minority unguarded write, so demanding unanimity would hide it).

Accesses are then checked against the owner on every path a second
thread can take: thread entry points (`Thread(target=)`, `do_*`
handlers, `Thread.run`, timers/executors) and public methods of
lock-owning classes (an object with a lock is shared by construction)
start with nothing held, and held sets propagate through resolvable
calls.  A read or write of an owned attr reachable on such a path
without the owner held is the exact shape of the PR 12 quota-bypass
race (`_queued_by_tenant` reading a swapped-out `_pending`).

`__init__` is exempt (the object is not shared yet), as are attrs whose
writes never synchronize (no inferred owner — plain config state).
"""
from __future__ import annotations

from tools.analyze.core import Finding
from tools.analyze.passes import _conc

PASS_ID = "guarded-field"
DESCRIPTION = ("attr guarded by a lock on most writes but touched "
               "without it on a thread-reachable path")

# object lifecycle methods where unshared access is the norm
_EXEMPT_METHODS = {"__init__", "__new__", "__post_init__", "__repr__",
                   "__str__", "__getstate__", "__setstate__",
                   "__del__", "__len__"}


def _infer_owners(scope):
    """attr -> (owner canonical lock, guarded, unguarded) for attrs with
    a majority-guarded write pattern."""
    writes = {}
    for meth in scope.methods.values():
        base = meth.name.split(".")[0]
        if base in _EXEMPT_METHODS:
            continue
        for a in meth.accesses:
            if a.kind == "write":
                writes.setdefault(a.attr, []).append(a)
    owners = {}
    for attr, ws in writes.items():
        by_lock = {}
        for w in ws:
            for h in w.held:
                by_lock[h] = by_lock.get(h, 0) + 1
        if not by_lock:
            continue
        lock, guarded = max(sorted(by_lock.items()),
                            key=lambda kv: kv[1])
        unguarded = sum(1 for w in ws if lock not in w.held)
        if guarded >= 2 and guarded > unguarded:
            owners[attr] = (lock, guarded, unguarded)
    return owners


def _seeds(model):
    for scope in model.class_scopes():
        if not scope.locks:
            continue
        for name in scope.thread_entries:
            yield scope, name
        for name, meth in scope.methods.items():
            # public surface of a lock-owning class: callable from any
            # thread with nothing held
            if not name.startswith("_") and not meth.is_nested:
                yield scope, name
    for scope in model.scopes:
        if scope.is_module:
            for name in scope.thread_entries:
                yield scope, name


def run(index):
    # one finding per (file, line): `self.x += 1` is a read AND a write
    # on the same line, but one diagnostic
    seen = set()
    for f in _findings(index):
        if f.key() not in seen:
            seen.add(f.key())
            yield f


def _findings(index):
    model = _conc.build(index)
    contexts = _conc.reachable_contexts(model, _seeds(model))
    for scope in model.class_scopes():
        if not scope.locks:
            continue
        owners = _infer_owners(scope)
        if not owners:
            continue
        for meth in scope.methods.values():
            base = meth.name.split(".")[0]
            if base in _EXEMPT_METHODS:
                continue
            ctxs = contexts.get((scope.key, meth.name))
            if not ctxs:
                continue        # never reached from a thread path
            for a in meth.accesses:
                owned = owners.get(a.attr)
                if not owned:
                    continue
                lock, guarded, unguarded = owned
                if lock in a.held:
                    continue
                qual = scope.qual(lock)
                if all(qual in c for c in ctxs):
                    continue    # every thread path in holds the owner
                yield Finding(
                    PASS_ID, scope.mod.rel, a.lineno,
                    f"{a.kind} of `{scope.name}.{a.attr}` without "
                    f"`{scope.display(lock)}` held — {guarded} of "
                    f"{guarded + unguarded} writes guard it with that "
                    f"lock, and `{meth.name}` runs on a thread path "
                    "that does not hold it (torn/stale state; the "
                    "PR 12 _pending-swap shape)")
