"""Pass `lock-order` — deadlock candidates from the acquired-while-held
graph (the lockdep idea, statically).

From the shared concurrency model (_conc.py): every `with self._b:`
reached while `self._a` is lexically held adds edge a -> b, and every
call made while holding `a` into a method that (transitively) acquires
`b` — including calls through typed attributes into other classes —
adds the same edge interprocedurally.  Two findings:

  * a CYCLE in the graph (a -> b somewhere, b -> a somewhere else) is a
    deadlock candidate: two threads taking the two paths concurrently
    stall forever.  One finding per cycle, anchored at its lexically
    first edge.
  * a SELF-EDGE on a non-reentrant `threading.Lock` (acquire while
    already held, possibly through a call chain) deadlocks a single
    thread on its own.  Re-entering an RLock or a Condition (whose
    default lock is an RLock) is legal and not flagged.

`summarize(index)` renders the whole acquisition-order table — the
canonical order the corpus actually follows — which the CLI emits into
the report under --tables/--json.
"""
from __future__ import annotations

from tools.analyze.core import Finding
from tools.analyze.passes import _conc

PASS_ID = "lock-order"
DESCRIPTION = ("acquired-while-held lock graph: cycles are deadlock "
               "candidates; re-acquiring a non-reentrant Lock "
               "self-deadlocks")


def _may_acquire(model):
    """(scope key, method) -> {(lock node, (rel, line, via)), ...} for
    every lock the method may acquire, transitively through resolvable
    calls.  A lock node is (scope key, canonical attr, display)."""
    direct = {}
    edges = {}          # method key -> resolved callee method keys
    meta = {}           # method key -> (scope, MethodModel)
    for scope in model.scopes:
        for meth in scope.methods.values():
            key = (scope.key, meth.name)
            meta[key] = (scope, meth)
            direct[key] = {
                ((*scope.qual(a.attr), scope.display(a.attr)),
                 (scope.mod.rel, a.lineno, meth.name))
                for a in meth.acquires}
            outs = set()
            for call in meth.calls:
                resolved = model.resolve_call(scope, call)
                if resolved:
                    outs.add((resolved[0].key, resolved[1].name))
            edges[key] = outs
    acq = {k: set(v) for k, v in direct.items()}
    changed = True
    while changed:
        changed = False
        for key, outs in edges.items():
            for out in outs:
                extra = acq.get(out, ()) - acq[key]
                if extra:
                    acq[key].update(extra)
                    changed = True
    return acq, meta


def _build_graph(index):
    """Edges {(a_node, b_node): (rel, line, via, how)} — `a` held when
    `b` is (or may be) acquired.  Memoised on the index: run() and
    summarize() share one interprocedural fixpoint."""
    cached = getattr(index, "_lock_graph", None)
    if cached is not None:
        return cached
    model = _conc.build(index)
    acq, _meta = _may_acquire(model)
    graph = {}

    def node(scope, attr):
        return (*scope.qual(attr), scope.display(attr))

    def add(a, b, site):
        graph.setdefault((a, b), site)

    for scope in model.scopes:
        for meth in scope.methods.values():
            for a in meth.acquires:
                for h in a.held:
                    add(node(scope, h), node(scope, a.attr),
                        (scope.mod.rel, a.lineno, meth.name, "with"))
            for call in meth.calls:
                if not call.held:
                    continue
                resolved = model.resolve_call(scope, call)
                if not resolved:
                    continue
                ckey = (resolved[0].key, resolved[1].name)
                for lock_node, _src in acq.get(ckey, ()):
                    for h in call.held:
                        add(node(scope, h), lock_node,
                            (scope.mod.rel, call.lineno, call.method,
                             f"call {call.callee}()"))
    index._lock_graph = (model, graph)
    return model, graph


def _cycles(graph):
    """Strongly connected components with >1 node, plus self-edges.
    Iterative Tarjan keeps deep chains off the Python stack."""
    nodes = sorted({n for e in graph for n in e})
    succs = {n: set() for n in nodes}
    for a, b in graph:
        if a != b:
            succs[a].add(b)
    idx, low, on, comp = {}, {}, set(), []
    stack, counter = [], [0]
    for start in nodes:
        if start in idx:
            continue
        work = [(start, iter(sorted(succs[start])))]
        idx[start] = low[start] = counter[0]
        counter[0] += 1
        stack.append(start)
        on.add(start)
        while work:
            v, it = work[-1]
            advanced = False
            for w in it:
                if w not in idx:
                    idx[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on.add(w)
                    work.append((w, iter(sorted(succs[w]))))
                    advanced = True
                    break
                if w in on:
                    low[v] = min(low[v], idx[w])
            if advanced:
                continue
            work.pop()
            if work:
                pv = work[-1][0]
                low[pv] = min(low[pv], low[v])
            if low[v] == idx[v]:
                scc = []
                while True:
                    w = stack.pop()
                    on.discard(w)
                    scc.append(w)
                    if w == v:
                        break
                if len(scc) > 1:
                    comp.append(sorted(scc))
    return comp


def run(index):
    model, graph = _build_graph(index)

    # self-edges: re-acquiring a non-reentrant Lock
    for (a, b), (rel, line, via, how) in sorted(graph.items(),
                                                key=lambda kv: kv[1][:2]):
        if a != b:
            continue
        scope = next((s for s in model.scopes if s.key == a[0]), None)
        kind = scope.locks.get(a[1]) if scope else None
        if kind != "lock":
            continue        # RLock/Condition re-entry is legal
        yield Finding(
            PASS_ID, rel, line,
            f"`{a[2]}` is a non-reentrant threading.Lock acquired while "
            f"already held (via {how} in {via}) — this thread deadlocks "
            "on itself; use an RLock or restructure the call")

    # cycles between distinct locks
    for scc in _cycles(graph):
        in_scc = {e: s for e, s in graph.items()
                  if e[0] in scc and e[1] in scc and e[0] != e[1]}
        if not in_scc:
            continue
        first = min(in_scc.items(), key=lambda kv: kv[1][:2])
        (rel, line, via, how) = first[1]
        order = " -> ".join(n[2] for n in scc)
        sites = "; ".join(
            f"{a[2]} -> {b[2]} at {s[0]}:{s[1]} ({s[3]} in {s[2]})"
            for (a, b), s in sorted(in_scc.items(),
                                    key=lambda kv: kv[1][:2]))
        yield Finding(
            PASS_ID, rel, line,
            f"lock-order cycle between {order}: {sites} — two threads "
            "taking these paths concurrently deadlock; pick one "
            "canonical order and acquire in it everywhere")


def summarize(index):
    """The canonical acquired-while-held table for the report."""
    _model, graph = _build_graph(index)
    lines = []
    for (a, b), (rel, line, via, how) in sorted(
            graph.items(), key=lambda kv: (kv[0][0][2], kv[0][1][2])):
        if a == b:
            continue
        lines.append(f"{a[2]} -> {b[2]}   [{rel}:{line} {how} in {via}]")
    return lines
