"""Shared concurrency model for the lock-order, guarded-field and
cv-discipline passes.

One walk over every class (plus a pseudo-scope for module level)
collects, per scope:

  * the lock inventory — attrs assigned from
    ``threading.Lock/RLock/Condition/Semaphore`` — with
    ``Condition(self._lock)`` wrappers canonicalised onto the lock they
    wrap (holding the condition IS holding that lock);
  * per-method lexical lock contexts: every ``with self._lock:`` nesting
    is tracked so each attribute access, call and acquisition carries
    the frozenset of locks held at that point;
  * the call graph: self-calls, calls through typed attributes
    (``self._store = StoreServer(...)`` then ``self._store.get()``),
    module-function calls, and every other call with its held set;
  * thread entry points: ``Thread(target=...)``/``Timer``/
    ``executor.submit`` targets, ``do_*`` handler methods, and ``run``
    on ``threading.Thread`` subclasses.  Nested ``def``s handed to a
    thread become pseudo-methods (named ``outer.inner``) whose bodies
    start with an EMPTY held set — the lexical context at the ``def``
    site does not survive into the thread.

The model is memoised on the Index (``index._conc``), so the three
passes share one walk.
"""
from __future__ import annotations

import ast
from dataclasses import dataclass, field

from tools.analyze.passes._util import dotted, func_name, terminal

LOCK_FACTORIES = {"Lock": "lock", "RLock": "rlock",
                  "Condition": "condition", "Semaphore": "semaphore",
                  "BoundedSemaphore": "semaphore"}

# method calls that mutate the receiver: `self._pending.append(x)` is a
# WRITE to _pending for guard-inference purposes
MUTATORS = {"append", "appendleft", "extend", "insert", "pop", "popleft",
            "remove", "discard", "clear", "add", "update", "setdefault",
            "popitem", "sort", "reverse", "rotate", "put", "put_nowait"}

_DEFS = (ast.FunctionDef, ast.AsyncFunctionDef)

# suffix disambiguating a module lock injected into a class whose own
# attrs include the same name (never a valid identifier tail)
_SHARED_MARK = "@module"


def _strip_shared(attr):
    return attr[:-len(_SHARED_MARK)] if attr.endswith(_SHARED_MARK) \
        else attr


@dataclass
class Access:
    """One `self.<attr>` touch inside a method body."""
    attr: str
    lineno: int
    kind: str                 # "read" | "write"
    held: frozenset           # canonical lock attrs held lexically
    method: str               # owning (pseudo-)method name
    node: ast.AST = None


@dataclass
class Acquire:
    """One `with self.<lock>:` entry."""
    attr: str                 # canonical lock attr being acquired
    raw_attr: str             # as written (the condition, if wrapped)
    lineno: int
    held: frozenset           # held BEFORE this acquisition
    method: str


@dataclass
class CallSite:
    callee: str               # terminal name of the called function
    kind: str                 # "self" | "attr" | "module" | "other"
    obj_attr: str | None      # for kind="attr": the receiver attr/name
    obj_term: str | None      # terminal of the receiver, any kind
    lineno: int
    held: frozenset
    method: str
    node: ast.Call = None


@dataclass
class MethodModel:
    name: str                 # dotted for nested defs ("m.inner")
    node: ast.AST
    accesses: list = field(default_factory=list)
    acquires: list = field(default_factory=list)
    calls: list = field(default_factory=list)
    is_nested: bool = False


@dataclass
class ScopeModel:
    mod: object               # core.Module
    name: str                 # class name, or "<module>"
    node: ast.AST
    is_module: bool = False
    bases: list = field(default_factory=list)      # dotted base names
    locks: dict = field(default_factory=dict)      # attr -> factory kind
    cv_lock: dict = field(default_factory=dict)    # condition -> wrapped lock
    attr_types: dict = field(default_factory=dict)  # attr -> class name
    methods: dict = field(default_factory=dict)    # name -> MethodModel
    thread_entries: set = field(default_factory=set)
    # module-global locks visible inside this class's methods (`with
    # _completer_lock:` in a method) — same lock OBJECT as the module
    # scope's, so qual() maps them onto the module's identity.  When a
    # module lock's name collides with one of the class's OWN lock
    # attrs, the injected token carries the _SHARED_MARK suffix so the
    # two never alias in a held-set.
    shared_locks: set = field(default_factory=set)
    # bare module-lock name -> token under which it lives in locks
    module_lock_alias: dict = field(default_factory=dict)

    def canon(self, attr):
        """Canonical lock name: a Condition wrapping self._lock IS
        self._lock for held-set purposes."""
        return self.cv_lock.get(attr, attr)

    @property
    def key(self):
        return (self.mod.rel, self.name)

    def qual(self, attr):
        """Cross-scope lock identity: (scope key, attr) — with a
        module-global lock used inside a class method resolving to the
        MODULE scope (under its bare module name), so held-sets
        propagated between a class and its module's functions agree."""
        if attr in self.shared_locks:
            return ((self.mod.rel, "<module>"), _strip_shared(attr))
        return (self.key, attr)

    def display(self, attr):
        if self.is_module or attr in self.shared_locks:
            return f"{self.mod.rel}.{_strip_shared(attr)}"
        return f"{self.name}.{attr}"

    def condition_locks(self):
        """Canonical lock attrs whose critical sections gate Condition
        waiters (a bare Condition, or the lock a Condition wraps)."""
        out = {a for a, k in self.locks.items() if k == "condition"
               and a not in self.cv_lock}
        out |= set(self.cv_lock.values())
        return out


class ConcModel:
    """All scopes across the corpus + cross-scope call resolution."""

    def __init__(self):
        self.scopes: list[ScopeModel] = []
        # class name -> ScopeModel; names colliding across modules are
        # dropped (resolution would be a guess)
        self.by_class: dict[str, ScopeModel] = {}
        self._ambiguous: set[str] = set()
        self._mod_scope: dict[str, ScopeModel] = {}

    def add(self, scope: ScopeModel):
        self.scopes.append(scope)
        if scope.is_module:
            self._mod_scope[scope.mod.rel] = scope
        elif scope.name in self.by_class:
            self._ambiguous.add(scope.name)
            del self.by_class[scope.name]
        elif scope.name not in self._ambiguous:
            self.by_class[scope.name] = scope

    def class_scopes(self):
        return [s for s in self.scopes if not s.is_module]

    def resolve_call(self, scope: ScopeModel, call: CallSite):
        """(scope, MethodModel) the call lands in, or None when the
        target is outside the model."""
        if call.kind == "self":
            m = scope.methods.get(call.callee)
            return (scope, m) if m else None
        if call.kind == "attr":
            cls = scope.attr_types.get(call.obj_attr)
            target = self.by_class.get(cls) if cls else None
            if target:
                m = target.methods.get(call.callee)
                return (target, m) if m else None
            return None
        if call.kind == "module":
            mscope = self._mod_scope.get(scope.mod.rel)
            if mscope:
                m = mscope.methods.get(call.callee)
                return (mscope, m) if m else None
        return None


def _lock_factory(value):
    """'lock'/'condition'/... when `value` is a threading factory call."""
    if isinstance(value, ast.Call):
        return LOCK_FACTORIES.get(func_name(value))
    return None


def _walk_skip_nested_classes(root):
    """ast.walk, but don't descend into ClassDefs other than `root`
    (a nested class's self is not the outer class's self)."""
    stack = [root]
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef) and child is not root:
                continue
            stack.append(child)


class _ScopeWalker:
    """Walks one scope's methods tracking the lexical held set."""

    def __init__(self, scope: ScopeModel, entry_marks):
        self.scope = scope
        self.entry_marks = entry_marks      # list[(class|None, name)]
        self.local_types = {}               # per-method: var -> ClassName

    def _owner_attr(self, expr):
        """`self.X` -> 'X' in a class scope; bare `X` at module scope.
        Inside a class, a bare name matching one of the module's locks
        also resolves (`with _completer_lock:` in a method)."""
        if self.scope.is_module:
            return expr.id if isinstance(expr, ast.Name) else None
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name) \
                and expr.value.id == "self":
            return expr.attr
        if isinstance(expr, ast.Name):
            return self.scope.module_lock_alias.get(expr.id)
        return None

    # -- per-method walk -----------------------------------------------------

    def walk_method(self, meth: MethodModel):
        self.local_types = {}
        self._walk(meth.node, frozenset(), meth)

    def _walk(self, node, held, meth):
        for child in ast.iter_child_nodes(node):
            self._dispatch(child, held, meth)

    def _dispatch(self, node, held, meth):
        if isinstance(node, _DEFS):
            sub = MethodModel(name=f"{meth.name}.{node.name}", node=node,
                              is_nested=True)
            self.scope.methods[sub.name] = sub
            # deferred execution: the def-site held set does not apply
            self._walk(node, frozenset(), sub)
            return
        if isinstance(node, (ast.Lambda, ast.ClassDef)):
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            self._walk_with(node, held, meth)
            return
        self._visit(node, held, meth)
        self._walk(node, held, meth)

    def _walk_with(self, node, held, meth):
        new_held = held
        for item in node.items:
            self._dispatch(item.context_expr, held, meth)
            attr = self._owner_attr(item.context_expr)
            if attr and self.scope.locks.get(attr) not in (None,
                                                           "semaphore"):
                canon = self.scope.canon(attr)
                meth.acquires.append(Acquire(
                    attr=canon, raw_attr=attr, lineno=node.lineno,
                    held=new_held, method=meth.name))
                new_held = new_held | {canon}
        for stmt in node.body:
            self._dispatch(stmt, new_held, meth)

    # -- node classification -------------------------------------------------

    def _visit(self, node, held, meth):
        if isinstance(node, ast.Call):
            self._visit_call(node, held, meth)
        elif isinstance(node, ast.Assign):
            self._note_types(node)
        attr = self._owner_attr(node)
        if attr is not None:
            self._visit_owner_access(node, attr, held, meth)

    def _note_types(self, assign):
        """`self.x = ClassName(...)` and `v = ClassName(...)` feed the
        attr/local type tables used to resolve cross-object calls."""
        if not isinstance(assign.value, ast.Call):
            return
        cls = func_name(assign.value)
        if not cls or not cls.lstrip("_")[:1].isupper():
            return      # class names only (incl. private _PyStoreServer)
        for t in assign.targets:
            a = self._owner_attr(t)
            if a:
                self.scope.attr_types[a] = cls
            if isinstance(t, ast.Name):
                self.local_types[t.id] = cls

    def _visit_call(self, call, held, meth):
        self._mark_thread_targets(call, meth)
        f = call.func
        if isinstance(f, ast.Attribute):
            obj_term = terminal(f.value)
            if not self.scope.is_module \
                    and isinstance(f.value, ast.Name) \
                    and f.value.id == "self":
                kind, obj_attr = "self", None
            else:
                obj_attr = self._owner_attr(f.value)
                kind = "attr" if obj_attr is not None else "other"
            callee = f.attr
        elif isinstance(f, ast.Name):
            kind, obj_attr, obj_term, callee = "module", None, None, f.id
        else:
            return
        meth.calls.append(CallSite(
            callee=callee, kind=kind, obj_attr=obj_attr,
            obj_term=obj_term, lineno=call.lineno, held=held,
            method=meth.name, node=call))

    def _mark_thread_targets(self, call, meth):
        """Thread(target=X) / Timer(t, X) / pool.submit(X, ...) mark X
        as a thread entry point.  Marks carry the scope OBJECT when the
        target is local (`self._tick`, a nested def) so two classes
        sharing a name cannot swallow each other's entries; only
        local-var-typed targets (`srv = Server(); Thread(target=
        srv.drain)`) go through the class-name table."""
        name = func_name(call)
        cands = []
        if name in ("Thread", "Timer"):
            cands += [kw.value for kw in call.keywords
                      if kw.arg in ("target", "function")]
            if name == "Timer" and len(call.args) >= 2:
                cands.append(call.args[1])
        elif name == "submit" and call.args:
            cands.append(call.args[0])
        for t in cands:
            a = self._owner_attr(t)
            if a is not None and not self.scope.is_module:
                self.entry_marks.append((self.scope, a))
            elif isinstance(t, ast.Name):
                nested = f"{meth.name}.{t.id}"
                if any(isinstance(n, _DEFS) and n.name == t.id
                       for n in ast.walk(meth.node)):
                    self.entry_marks.append((self.scope, nested))
                else:
                    self.entry_marks.append((None, t.id))
            elif isinstance(t, ast.Attribute) \
                    and isinstance(t.value, ast.Name):
                cls = self.local_types.get(t.value.id)
                if cls:
                    self.entry_marks.append((cls, t.attr))

    def _visit_owner_access(self, node, attr, held, meth):
        scope = self.scope
        if scope.is_module:
            return          # guarded-field is a class-scope analysis
        if attr in scope.locks or attr in scope.methods:
            return
        parent = getattr(node, "parent", None)
        if isinstance(parent, ast.Call) and parent.func is node:
            return          # self.m() — recorded as a call instead
        kind = "read"
        if isinstance(node.ctx, (ast.Store, ast.Del)):
            kind = "write"
        elif isinstance(parent, ast.Attribute) and parent.value is node:
            gp = getattr(parent, "parent", None)
            if isinstance(gp, ast.Call) and gp.func is parent \
                    and parent.attr in MUTATORS:
                kind = "write"
        elif isinstance(parent, ast.Subscript) and parent.value is node \
                and isinstance(parent.ctx, (ast.Store, ast.Del)):
            kind = "write"
        meth.accesses.append(Access(attr=attr, lineno=node.lineno,
                                    kind=kind, held=held,
                                    method=meth.name, node=node))


def _collect_locks(scope: ScopeModel):
    """Lock inventory: every `self.X = threading.Lock()` (class scope)
    or global `X = threading.Lock()` (module scope) in the scope."""

    def match(t):
        if scope.is_module:
            return t.id if isinstance(t, ast.Name) else None
        if isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name) \
                and t.value.id == "self":
            return t.attr
        return None

    if scope.is_module:
        # module-body assignments, plus reassignments under a `global`
        # declaration elsewhere in the module
        globals_ = {n for g in ast.walk(scope.node)
                    if isinstance(g, ast.Global) for n in g.names}
        body_names = {t.id for n in scope.node.body
                      if isinstance(n, ast.Assign)
                      for t in n.targets if isinstance(t, ast.Name)}
        allowed = globals_ | body_names
        nodes = [n for n in ast.walk(scope.node)
                 if isinstance(n, ast.Assign)]
    else:
        allowed = None
        nodes = [n for n in _walk_skip_nested_classes(scope.node)
                 if isinstance(n, ast.Assign)]
    for node in nodes:
        kind = _lock_factory(node.value)
        if kind is None:
            continue
        for t in node.targets:
            a = match(t)
            if not a or (allowed is not None and a not in allowed):
                continue
            scope.locks[a] = kind
            if kind == "condition" and node.value.args:
                w = match(node.value.args[0])
                if w:
                    scope.cv_lock[a] = w


def build(index) -> ConcModel:
    """Build (or fetch the memoised) corpus concurrency model."""
    cached = getattr(index, "_conc", None)
    if cached is not None:
        return cached
    model = ConcModel()
    entry_marks = []              # (class name | None for module, meth)
    walkers = []
    for mod in index.modules:
        if mod.tree is None:
            continue
        mscope = ScopeModel(mod=mod, name="<module>", node=mod.tree,
                            is_module=True)
        _collect_locks(mscope)
        for fn in mod.tree.body:
            if isinstance(fn, _DEFS):
                mscope.methods[fn.name] = MethodModel(name=fn.name,
                                                      node=fn)
        model.add(mscope)
        walkers.append(_ScopeWalker(mscope, entry_marks))
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            scope = ScopeModel(mod=mod, name=node.name, node=node,
                               bases=[dotted(b) or "" for b in node.bases])
            _collect_locks(scope)
            # module-global locks are visible (and common — watchdog's
            # _completer_cv) inside class methods: inject them, marked
            # shared so qual() keeps one identity with the module
            # scope.  A name the class ALSO owns as its own lock attr
            # gets the _SHARED_MARK token — the two are different
            # locks and must not alias in a held-set.
            for lname, lkind in mscope.locks.items():
                token = lname if lname not in scope.locks \
                    else lname + _SHARED_MARK
                scope.locks[token] = lkind
                scope.shared_locks.add(token)
                scope.module_lock_alias[lname] = token
            for lcv, ltgt in mscope.cv_lock.items():
                cv_tok = scope.module_lock_alias.get(lcv)
                tgt_tok = scope.module_lock_alias.get(ltgt)
                if cv_tok and tgt_tok:
                    scope.cv_lock[cv_tok] = tgt_tok
            for item in node.body:
                if isinstance(item, _DEFS):
                    scope.methods[item.name] = MethodModel(
                        name=item.name, node=item)
            model.add(scope)
            walkers.append(_ScopeWalker(scope, entry_marks))
    for w in walkers:
        for meth in list(w.scope.methods.values()):
            if not meth.is_nested:
                w.walk_method(meth)
    # fold the entry marks into their scopes
    for scope in model.scopes:
        for base in scope.bases:
            if base and base.split(".")[-1] == "Thread" \
                    and "run" in scope.methods:
                scope.thread_entries.add("run")
        for name in scope.methods:
            if name.split(".")[-1].startswith("do_"):
                scope.thread_entries.add(name)
    for owner, meth in entry_marks:
        if owner is None:
            for scope in model.scopes:
                if scope.is_module and meth in scope.methods:
                    scope.thread_entries.add(meth)
        elif isinstance(owner, ScopeModel):
            if meth in owner.methods:
                owner.thread_entries.add(meth)
        else:
            scope = model.by_class.get(owner)
            if scope and meth in scope.methods:
                scope.thread_entries.add(meth)
    index._conc = model
    return model


def reachable_contexts(model: ConcModel, seeds):
    """Worklist over the call graph: which held-set contexts can each
    method run under, starting from `seeds` (an iterable of (scope,
    method name) pairs that run with NOTHING held — thread entry
    points, and optionally externally-callable methods)?  Returns
    {(scope key, method name): set[frozenset]}.  A call site adds its
    lexical held set on top of the caller's context.  Context members
    are (scope key, canonical attr) pairs — two classes both naming
    their lock `_lock` must not alias."""
    contexts: dict[tuple, set] = {}
    work = []
    for scope, name in seeds:
        key = (scope.key, name)
        if frozenset() not in contexts.setdefault(key, set()):
            contexts[key].add(frozenset())
            work.append((scope, name, frozenset()))
    while work:
        scope, name, ctx = work.pop()
        meth = scope.methods.get(name)
        if meth is None:
            continue
        for call in meth.calls:
            resolved = model.resolve_call(scope, call)
            if not resolved:
                continue
            tscope, tmeth = resolved
            nctx = frozenset(ctx | {scope.qual(h) for h in call.held})
            key = (tscope.key, tmeth.name)
            if nctx not in contexts.setdefault(key, set()):
                contexts[key].add(nctx)
                work.append((tscope, tmeth.name, nctx))
    return contexts
