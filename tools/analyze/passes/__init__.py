"""Pass registry: ordered list of pass modules, each exposing
PASS_ID, DESCRIPTION and run(index) -> iterable[Finding].  A pass may
also expose summarize(index) -> list[str] for the report's notes
section (e.g. lock-order's canonical acquisition table)."""
from tools.analyze.passes import (chaos_points, cv_discipline, gating,
                                  guarded_field, hot_path, jax_compat,
                                  jax_hazards, lock_order, metric_names,
                                  swallow, threads)

ALL_PASSES = [
    jax_compat,        # jax-compat
    chaos_points,      # chaos-points
    metric_names,      # metric-names
    hot_path,          # hot-path-sync
    threads,           # thread-discipline
    swallow,           # silent-swallow
    gating,            # disabled-gate
    lock_order,        # lock-order
    guarded_field,     # guarded-field
    cv_discipline,     # cv-discipline
    jax_hazards,       # jax-hazards
]

BY_ID = {p.PASS_ID: p for p in ALL_PASSES}
