"""Pass registry: ordered list of pass modules, each exposing
PASS_ID, DESCRIPTION and run(index) -> iterable[Finding]."""
from tools.analyze.passes import (chaos_points, gating, hot_path,
                                  jax_compat, metric_names, swallow,
                                  threads)

ALL_PASSES = [
    jax_compat,        # jax-compat
    chaos_points,      # chaos-points
    metric_names,      # metric-names
    hot_path,          # hot-path-sync
    threads,           # thread-discipline
    swallow,           # silent-swallow
    gating,            # disabled-gate
]

BY_ID = {p.PASS_ID: p for p in ALL_PASSES}
