"""Pass `cv-discipline` — the three classic Condition mistakes.

Over every `threading.Condition` in the shared concurrency model
(class attrs and module globals alike):

  1. `cv.wait()` not inside a `while` predicate loop.  Spurious wakeups
     and stolen wakeups are real; an `if`-guarded or bare wait observes
     a predicate that may already be false again.  `wait_for` carries
     its own loop and is exempt.
  2. `cv.notify()` / `notify_all()` / `wait()` on a path that cannot be
     holding the condition's lock — a guaranteed RuntimeError("cannot
     notify on un-acquired lock") the first time that path runs.  The
     check is path-aware: a private helper that is only ever called
     from inside `with cv:` blocks is fine.
  3. Replies/IO performed while holding a condition's critical section
     — `sendall`/`send_response`/`wfile.write` and friends under the
     cv convoy every waiter behind one slow peer (the PR 8 store-server
     convoy, generalized).
"""
from __future__ import annotations

import ast

from tools.analyze.core import Finding
from tools.analyze.passes import _conc
from tools.analyze.passes._util import call_snippet

PASS_ID = "cv-discipline"
DESCRIPTION = ("Condition.wait needs a while-predicate loop and the "
               "lock held; notify needs the lock; no replies/IO inside "
               "a condition's critical section")

# reply/IO calls that convoy cv waiters when made under the condition
_IO_ATTRS = {"sendall", "send_response", "send_header", "end_headers",
             "send_error"}
_IO_STREAMY = {"write", "flush", "send"}
_IO_BASES = {"wfile", "sock", "socket", "conn", "connection", "client",
             "stream", "resp", "response"}


def _cv_calls(scope):
    """CallSites on this scope's Condition attrs."""
    cvs = {a for a, k in scope.locks.items() if k == "condition"}
    for meth in scope.methods.values():
        for call in meth.calls:
            if call.kind in ("attr", "other") and call.obj_attr in cvs:
                yield call, call.obj_attr, meth


def _in_while(node, fn_node):
    """Is `node` (a Call) lexically inside a While within its function?"""
    cur = getattr(node, "parent", None)
    while cur is not None and cur is not fn_node:
        if isinstance(cur, ast.While):
            return True
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)):
            return False
        cur = getattr(cur, "parent", None)
    return False


def _seeds(model):
    # resolve every call once: a module helper invoked only from a
    # class method's `with cv:` block IS called — it must inherit that
    # context, not be seeded as externally-callable-bare
    called = set()
    for scope in model.scopes:
        for m in scope.methods.values():
            for c in m.calls:
                r = model.resolve_call(scope, c)
                if r:
                    called.add((r[0].key, r[1].name))
    for scope in model.scopes:
        for name in scope.thread_entries:
            yield scope, name
        for name, meth in scope.methods.items():
            public = not name.startswith("_") and not meth.is_nested
            if public or ((scope.key, name) not in called
                          and not meth.is_nested):
                yield scope, name


def run(index):
    model = _conc.build(index)
    contexts = None     # built lazily: most corpora have few cv sites

    def lockless_path(scope, meth, call, lock):
        """True when some reachable context runs `meth` without `lock`
        held at this call site (lexically or from any caller)."""
        nonlocal contexts
        if lock in call.held:
            return False
        if contexts is None:
            contexts = _conc.reachable_contexts(model, _seeds(model))
        ctxs = contexts.get((scope.key, meth.name))
        if not ctxs:
            return True     # unreached ≈ externally called bare
        qual = scope.qual(lock)
        return any(qual not in c for c in ctxs)

    for scope in model.scopes:
        for call, cv, meth in _cv_calls(scope):
            lock = scope.canon(cv)
            if call.callee == "wait":
                fn = meth.node
                if not _in_while(call.node, fn):
                    yield Finding(
                        PASS_ID, scope.mod.rel, call.lineno,
                        f"`{scope.display(cv)}.wait()` outside a "
                        "`while <predicate>:` loop — spurious/stolen "
                        "wakeups make a bare or if-guarded wait observe "
                        "a predicate that is already false; re-check in "
                        "a while loop (or use wait_for)")
            if call.callee in ("notify", "notify_all", "wait"):
                if lockless_path(scope, meth, call, lock):
                    yield Finding(
                        PASS_ID, scope.mod.rel, call.lineno,
                        f"`{scope.display(cv)}.{call.callee}()` on a "
                        "path that does not hold the condition's lock "
                        "— RuntimeError('cannot notify/wait on "
                        "un-acquired lock') the first time this path "
                        "runs; wrap it in `with "
                        f"{scope.display(cv)}:`")

        # IO inside any condition's critical section
        gates = scope.condition_locks()
        if not gates:
            continue
        for meth in scope.methods.values():
            for call in meth.calls:
                held_cvs = gates & call.held
                if not held_cvs:
                    continue
                is_io = call.callee in _IO_ATTRS or (
                    call.callee in _IO_STREAMY
                    and call.obj_term in _IO_BASES)
                if not is_io:
                    continue
                cv = sorted(held_cvs)[0]
                yield Finding(
                    PASS_ID, scope.mod.rel, call.lineno,
                    f"{call_snippet(call.node)}: reply/IO while "
                    f"holding `{scope.display(cv)}` (a Condition's "
                    "critical section) — one slow peer convoys every "
                    "waiter (the PR 8 store-server bug); buffer under "
                    "the lock, send after release")
