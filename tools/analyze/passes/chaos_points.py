"""Pass `chaos-points` — every chaos injection site is registered.

Port of tools/check_chaos_points.py: `distributed/chaos.py` carries
POINTS, the documented registry of every named fault-injection site.
An injection call whose site literal is not registered is invisible to
operators reading the catalogue, so every
`chaos.should_fire/maybe_*("site")` call in paddle_tpu/ must name a
registered site (registry keys ending in "/" cover dynamically-suffixed
f-string sites by static prefix), and the site argument must BE a
literal/f-string — a variable cannot be audited.

The legacy `scan(root) -> (violations, seen, points)` surface is kept
for tools/check_chaos_points.py (now a shim) and its tests.
"""
from __future__ import annotations

import ast
import importlib.util
import os

from tools.analyze.core import Finding, build_index

PASS_ID = "chaos-points"
DESCRIPTION = ("chaos injection sites must be string literals "
               "registered in distributed/chaos.py POINTS")

INJECTORS = {"should_fire", "maybe_delay", "maybe_drop",
             "maybe_preempt", "maybe_corrupt_file", "grad_poison",
             "loss_spike"}

# the registry module itself (its function bodies pass `site` variables
# around, which is the implementation, not an injection site)
ALLOWED = {os.path.join("paddle_tpu", "distributed", "chaos.py")}


def _load_points(root: str) -> dict:
    path = os.path.join(root, "paddle_tpu", "distributed", "chaos.py")
    if not os.path.isfile(path):
        return {}                   # no registry: nothing to audit
    spec = importlib.util.spec_from_file_location("_chaos_registry", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)        # stdlib-only module (no jax)
    return dict(getattr(mod, "POINTS", {}))


def _site_of(node):
    """(site, is_prefix) of an injection call's first argument, or
    (None, False) when it is not a literal. An f-string yields its
    static leading text as a prefix."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value, False
    if isinstance(node, ast.JoinedStr):
        if node.values and isinstance(node.values[0], ast.Constant) \
                and isinstance(node.values[0].value, str):
            return node.values[0].value, True
        return None, False
    return None, False


def _covered(site: str, is_prefix: bool, points: dict) -> bool:
    if not is_prefix:
        return site in points or any(
            k.endswith("/") and site.startswith(k) for k in points)
    # an f-string's static prefix must match a registered prefix key
    return any(k.endswith("/") and site.startswith(k) for k in points)


def _scan_index(index):
    """(violations, seen, points): violations are (rel, lineno, call,
    problem); seen is the set of (site, is_prefix) literals."""
    points = _load_points(index.root)
    violations = []
    seen = set()
    for mod in index.under("paddle_tpu"):
        if mod.rel in ALLOWED or mod.tree is None:
            continue
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            name = (func.attr if isinstance(func, ast.Attribute)
                    else func.id if isinstance(func, ast.Name)
                    else None)
            if name not in INJECTORS or not node.args:
                continue
            site, is_prefix = _site_of(node.args[0])
            call = f"{name}({ast.unparse(node.args[0])})"
            if site is None:
                violations.append(
                    (mod.rel, node.lineno, call,
                     "site is not a string literal / f-string — "
                     "cannot be audited against chaos.POINTS"))
                continue
            seen.add((site, is_prefix))
            if not _covered(site, is_prefix, points):
                violations.append(
                    (mod.rel, node.lineno, call,
                     f"site {site!r} is not in the chaos.POINTS "
                     "registry (distributed/chaos.py) — document "
                     "it there"))
    return violations, seen, points


def run(index):
    violations, _seen, _points = _scan_index(index)
    for rel, no, call, why in violations:
        yield Finding(PASS_ID, rel, no, f"{call}: {why}")


def scan(root: str):
    """Legacy surface (tools/check_chaos_points.py shim + its tests).
    Indexes only paddle_tpu/ — all this scanner ever looked at."""
    return _scan_index(build_index(root, subdirs=("paddle_tpu",),
                                   files=()))
