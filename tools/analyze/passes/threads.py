"""Pass `thread-discipline` — threads must be reapable, locks must stay
off blocking calls.

Two invariants, both learned the hard way (PR 5's `no_leaked_threads`
fixture, PR 3's signal-handler deadlock dodge):

1. Every `threading.Thread(...)` must be `daemon=True` or be bound to
   a name/attribute that some code path `.join()`s (the close()/stop()
   contract). A non-daemon thread with no reachable join hangs
   interpreter exit and is invisible in a passing test.

2. A lock must not be held across a blocking call: `time.sleep`,
   thread `.join()`, a `.get()` with no timeout, socket I/O, or a
   `.wait()` on a DIFFERENT object than the one the `with` holds
   (Condition.wait on its own condition releases the lock and is the
   sanctioned pattern). Any of these inside `with <lock>:` is the
   classic deadlock/convoy shape.

Lock-like contexts are names/attributes assigned from
`threading.Lock/RLock/Condition/Semaphore` anywhere in the module,
plus anything whose terminal name looks like a lock (`_lock`, `cv`,
`_cond`, `mutex`).
"""
from __future__ import annotations

import ast
import re

from tools.analyze.core import Finding
from tools.analyze.passes._util import (call_snippet, func_name,
                                        terminal, walk_no_defs)

PASS_ID = "thread-discipline"
DESCRIPTION = ("threads need daemon=True or a reachable join(); locks "
               "must not be held across blocking calls")

_LOCK_FACTORIES = {"Lock", "RLock", "Condition", "Semaphore",
                   "BoundedSemaphore"}
_LOCK_NAME_HINT = re.compile(
    r"(^|_)(lock|rlock|mutex|cv|cond|condition)s?$", re.I)
_SOCKET_BLOCKERS = {"recv", "recv_into", "accept", "connect", "sendall",
                    "serve_forever", "makefile"}
_DEFS = (ast.FunctionDef, ast.AsyncFunctionDef)


def _assigned_lock_names(tree):
    """Terminal names bound to threading lock objects anywhere in the
    module (class-agnostic: one module, one namespace of lock names)."""
    names = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and isinstance(node.value,
                                                       ast.Call):
            if func_name(node.value) in _LOCK_FACTORIES:
                for t in node.targets:
                    term = terminal(t)
                    if term:
                        names.add(term)
    return names


def _is_locklike(expr, lock_names):
    term = terminal(expr)
    if term is None:
        return None
    if term in lock_names or _LOCK_NAME_HINT.search(term):
        return term
    return None


def _base_terminal(attr_call_func):
    """For `a.b.wait` return 'b' (the object being waited on)."""
    if isinstance(attr_call_func, ast.Attribute):
        return terminal(attr_call_func.value)
    return None


def _blocking_reason(call, lock_term):
    f = call.func
    if isinstance(f, ast.Attribute):
        a = f.attr
        if a == "sleep":
            return "time.sleep() while holding the lock"
        if a == "join":
            pos = call.args
            if not pos or (len(pos) == 1
                           and isinstance(pos[0], ast.Constant)
                           and isinstance(pos[0].value, (int, float))):
                return "thread join() while holding the lock"
            return None             # str.join/os.path.join shapes
        if a == "get" and not call.args \
                and not any(kw.arg == "timeout" for kw in call.keywords):
            return ("blocking .get() with no timeout while holding "
                    "the lock")
        if a in _SOCKET_BLOCKERS:
            return f"socket/server .{a}() while holding the lock"
        if a in ("wait", "wait_for"):
            base = _base_terminal(f)
            if base is not None and base != lock_term:
                return (f"waiting on `{base}` while holding lock "
                        f"`{lock_term}` (only the lock's own "
                        "condition may wait here)")
            return None
    elif isinstance(f, ast.Name) and f.id == "sleep":
        return "sleep() while holding the lock"
    return None


def _check_with_blocks(mod, lock_names):
    for node in ast.walk(mod.tree):
        if not isinstance(node, (ast.With, ast.AsyncWith)):
            continue
        for item in node.items:
            lock_term = _is_locklike(item.context_expr, lock_names)
            if lock_term is None:
                continue
            for stmt in node.body:
                for sub in walk_no_defs(stmt):
                    if not isinstance(sub, ast.Call):
                        continue
                    why = _blocking_reason(sub, lock_term)
                    if why:
                        yield Finding(
                            PASS_ID, mod.rel, sub.lineno,
                            f"{call_snippet(sub)}: {why} — the "
                            "deadlock/convoy shape; move the call "
                            "outside the critical section")
            break   # one lock-like item is enough to audit the body


def _joined_terminals(tree):
    """Terminal names X for which `X.join(...)` appears anywhere."""
    joined = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and isinstance(node.func,
                                                     ast.Attribute) \
                and node.func.attr == "join":
            term = terminal(node.func.value)
            if term:
                joined.add(term)
    return joined


def _binding_terminal(call):
    """The name a Thread(...) result is bound to: `t = Thread(...)` ->
    't', `self._thread = Thread(...)` -> '_thread', appended into a
    container -> the container's name; None when unbound."""
    parent = getattr(call, "parent", None)
    if isinstance(parent, (ast.Assign, ast.AnnAssign)):
        targets = parent.targets if isinstance(parent, ast.Assign) \
            else [parent.target]
        for t in targets:
            term = terminal(t)
            if term:
                return term
    if isinstance(parent, ast.Call) and isinstance(parent.func,
                                                   ast.Attribute) \
            and parent.func.attr == "append":
        return terminal(parent.func.value)
    return None


def _check_thread_creations(mod):
    joined = _joined_terminals(mod.tree)
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call) \
                or func_name(node) != "Thread":
            continue
        daemon = next((kw.value for kw in node.keywords
                       if kw.arg == "daemon"), None)
        if daemon is not None:
            if isinstance(daemon, ast.Constant):
                if daemon.value:
                    continue        # daemon=True: dies with the process
            else:
                continue            # daemon=<expr>: can't audit
        bound = _binding_terminal(node)
        if bound is not None and bound in joined:
            continue                # join() on the binding exists
        where = (f"bound to `{bound}` which is never join()ed"
                 if bound else "never bound (so never join()ed)")
        yield Finding(
            PASS_ID, mod.rel, node.lineno,
            f"non-daemon threading.Thread {where} — pass daemon=True "
            "or join it in a close()/stop() path (a leaked non-daemon "
            "thread hangs interpreter exit)")


def run(index):
    for mod in index.modules:
        if mod.tree is None:
            continue
        lock_names = _assigned_lock_names(mod.tree)
        yield from _check_with_blocks(mod, lock_names)
        yield from _check_thread_creations(mod)
