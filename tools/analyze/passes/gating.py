"""Pass `disabled-gate` — instruments stay free when switched off.

The contract every PR since PR 1 asserts by hand-written test: with
observability/chaos disabled, an instrumented hot path pays exactly one
module-attribute load and a falsy branch. That only holds if every
call site OUTSIDE the instrument's own package sits behind the gate:

    if observability.ENABLED:
        observability.inc("store.rpc.retries")

    if chaos.ENABLED and chaos.should_fire("ckpt.async.fail"):
        ...

This pass finds `observability.inc/observe/set_gauge` and
`chaos.should_fire/maybe_*` calls in paddle_tpu/ (outside
paddle_tpu/observability/ and distributed/chaos.py) that are NOT
dominated by an `<module>.ENABLED` check — whether the module is
imported `from paddle_tpu import observability [as x]`, plainly
(`import paddle_tpu.observability[ as y]`), or the instrument itself
is imported directly (`from paddle_tpu.observability import inc`,
which leaves no module object to gate on and is flagged unless a
same-kind module alias's ENABLED dominates). Recognized gate shapes:

  - an enclosing `if <mod>.ENABLED [and ...]:` (call in the body), or
    `if not <mod>.ENABLED:` (call in the else branch),
  - a conditional expression `X if <mod>.ENABLED else Y`,
  - short-circuit `<mod>.ENABLED and <call>`,
  - an early-out guard earlier in the same function:
    `if not <mod>.ENABLED: return/raise/continue`.
"""
from __future__ import annotations

import ast
import os

from tools.analyze.core import Finding
from tools.analyze.passes._util import call_snippet, terminal

PASS_ID = "disabled-gate"
DESCRIPTION = ("observability/chaos instrument calls outside their "
               "packages must sit behind the <module>.ENABLED gate")

OBS_INSTRUMENTS = {"inc", "observe", "set_gauge"}
CHAOS_INSTRUMENTS = {"should_fire", "maybe_delay", "maybe_drop",
                     "maybe_preempt", "maybe_corrupt_file",
                     "grad_poison", "loss_spike"}

# instrument home packages: call sites inside them ARE the plumbing
_EXEMPT_PREFIXES = (os.path.join("paddle_tpu", "observability") + os.sep,)
_EXEMPT_FILES = {os.path.join("paddle_tpu", "distributed", "chaos.py")}


_HOMES = {"paddle_tpu.observability": "obs",
          "paddle_tpu.distributed.chaos": "chaos"}


def _aliases(tree):
    """(aliases, bare): `aliases` maps module alias -> 'obs'/'chaos'
    from `from paddle_tpu import observability [as x]`,
    `from paddle_tpu.distributed import chaos [as y]`, and plain
    `import paddle_tpu....[ as z]` (without `as`, the call spells
    `paddle_tpu.observability.inc(...)` whose terminal attribute IS the
    module name). `bare` maps directly-imported instrument names
    (`from paddle_tpu.observability import inc [as i]`) -> kind —
    those call sites have no module object to gate on and are audited
    separately."""
    aliases, bare = {}, {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            for a in node.names:
                if node.module == "paddle_tpu" \
                        and a.name == "observability":
                    aliases[a.asname or a.name] = "obs"
                elif node.module == "paddle_tpu.distributed" \
                        and a.name == "chaos":
                    aliases[a.asname or a.name] = "chaos"
                elif node.module in _HOMES:
                    kind = _HOMES[node.module]
                    wanted = OBS_INSTRUMENTS if kind == "obs" \
                        else CHAOS_INSTRUMENTS
                    if a.name in wanted:
                        bare[a.asname or a.name] = kind
        elif isinstance(node, ast.Import):
            for a in node.names:
                kind = _HOMES.get(a.name)
                if kind:
                    # `import paddle_tpu.observability as o` -> o.inc;
                    # without `as`, paddle_tpu.observability.inc whose
                    # terminal() is the last dotted component
                    aliases[a.asname or a.name.rsplit(".", 1)[-1]] = kind
    return aliases, bare


def _enabled_polarities(test, alias):
    """Polarities at which `<alias>.ENABLED` occurs in `test`: True for
    a plain mention, False under an odd number of `not`s."""
    found = set()

    def visit(node, neg):
        if isinstance(node, ast.UnaryOp) and isinstance(node.op,
                                                        ast.Not):
            visit(node.operand, not neg)
            return
        if isinstance(node, ast.Attribute) and node.attr == "ENABLED" \
                and terminal(node.value) == alias:
            found.add(not neg)
        for child in ast.iter_child_nodes(node):
            visit(child, neg)

    visit(test, False)
    return found


def _stmt_guards(fn_body, before_stmt, alias):
    """True when a statement before `before_stmt` in the same body is
    `if not <alias>.ENABLED: return/raise/continue`."""
    for stmt in fn_body:
        if stmt is before_stmt:
            return False
        if isinstance(stmt, ast.If) \
                and False in _enabled_polarities(stmt.test, alias) \
                and stmt.body \
                and isinstance(stmt.body[-1],
                               (ast.Return, ast.Raise, ast.Continue)):
            return True
    return False


def _is_gated(call, alias):
    child = call
    node = getattr(call, "parent", None)
    while node is not None:
        if isinstance(node, ast.If):
            pol = _enabled_polarities(node.test, alias)
            if child in node.body and True in pol:
                return True
            if child in node.orelse and False in pol:
                return True
        elif isinstance(node, ast.IfExp):
            pol = _enabled_polarities(node.test, alias)
            if child is node.body and True in pol:
                return True
            if child is node.orelse and False in pol:
                return True
        elif isinstance(node, ast.BoolOp) and isinstance(node.op,
                                                         ast.And):
            idx = node.values.index(child) if child in node.values \
                else len(node.values)
            for earlier in node.values[:idx]:
                if True in _enabled_polarities(earlier, alias):
                    return True
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # early-out guard before the statement containing the call
            stmt = child
            while stmt is not None and stmt not in node.body:
                stmt = getattr(stmt, "parent", None)
            if stmt is not None and _stmt_guards(node.body, stmt,
                                                 alias):
                return True
            return False
        elif isinstance(node, (ast.Lambda, ast.Module, ast.ClassDef)):
            return False
        child, node = node, getattr(node, "parent", None)
    return False


def run(index):
    for mod in index.under("paddle_tpu"):
        if mod.tree is None or mod.rel in _EXEMPT_FILES \
                or mod.rel.startswith(_EXEMPT_PREFIXES):
            continue
        aliases, bare = _aliases(mod.tree)
        if not aliases and not bare:
            continue
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            if isinstance(node.func, ast.Attribute):
                alias = terminal(node.func.value)
                kind = aliases.get(alias)
                if kind is None:
                    continue
                wanted = OBS_INSTRUMENTS if kind == "obs" \
                    else CHAOS_INSTRUMENTS
                if node.func.attr not in wanted:
                    continue
                if _is_gated(node, alias):
                    continue
                yield Finding(
                    PASS_ID, mod.rel, node.lineno,
                    f"{call_snippet(node)} is not behind `if "
                    f"{alias}.ENABLED:` — the disabled path must cost "
                    "one attribute check (gate it, or justify with a "
                    "suppression)")
            elif isinstance(node.func, ast.Name):
                # directly-imported instrument (`from ... import inc`):
                # gated only if some same-kind module alias's ENABLED
                # dominates the call
                kind = bare.get(node.func.id)
                if kind is None:
                    continue
                mods = [a for a, k in aliases.items() if k == kind]
                if any(_is_gated(node, a) for a in mods):
                    continue
                gate = f"{mods[0]}.ENABLED" if mods else \
                    "the module's ENABLED attribute (import the " \
                    "module, not the function)"
                yield Finding(
                    PASS_ID, mod.rel, node.lineno,
                    f"{call_snippet(node)} is not behind `if {gate}:` "
                    "— the disabled path must cost one attribute "
                    "check (gate it, or justify with a suppression)")
