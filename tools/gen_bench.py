"""KV-cache decode benchmark on the attached TPU chip.

Run single-process under the default (axon) env:
    python tools/gen_bench.py [batch] [prompt_len] [new_tokens]
Measures, for an 8L/1024h bf16 Llama (the serving config BASELINE.md's
latency table uses): prefill latency, per-token decode latency, and
decode throughput through models.generation's jitted prefill/decode
steps. NOTE (this rig): each decode step pays a ~100ms synchronous
tunnel round trip for the token fetch, which floors per-token latency —
record the numbers as tunnel-inclusive serving latency, not chip-only
step time.

Round-3 measurement (v5e tunnel, b1 s512, probe run): prefill program
compile ~183s and decode ~202s (remote axon compiler; one-time per
shape), steady decode **100-200 ms/token** — entirely the tunnel RTT
floor (the serving table's 117.7ms single-forward p50 shows the same
floor), chip-side decode is sub-ms at this size. Budget >=10 min for a
cold run of this tool on this rig."""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.models import LlamaForCausalLM
from paddle_tpu.models.llama import tiny_llama_config
from paddle_tpu.models.generation import generate_stream

b = int(sys.argv[1]) if len(sys.argv) > 1 else 1
s = int(sys.argv[2]) if len(sys.argv) > 2 else 512
new = int(sys.argv[3]) if len(sys.argv) > 3 else 64

paddle.seed(0)
cfg = tiny_llama_config(num_hidden_layers=8, hidden_size=1024,
                        intermediate_size=2816, num_attention_heads=16,
                        num_key_value_heads=8, vocab_size=16384,
                        max_position_embeddings=s + new, seq_length=s)
model = LlamaForCausalLM(cfg)
model.eval()
model = paddle.amp.decorate(models=model, level="O2", dtype="bfloat16")
ids = np.random.RandomState(0).randint(0, cfg.vocab_size,
                                       (b, s)).astype("int32")

# warm (compile prefill + decode) — SAME max_new_tokens as the measured
# pass: the cache buffer shape is s+new, so a different warm length
# would leave the measured pass recompiling both programs
t0 = time.perf_counter()
for i, tok in enumerate(generate_stream(model, ids, max_new_tokens=new)):
    if i == 0:
        print(f"compile+first-token: {time.perf_counter()-t0:.1f}s",
              flush=True)
    if i == 1:
        print(f"decode compiled at {time.perf_counter()-t0:.1f}s",
              flush=True)
        break

# measured pass
t0 = time.perf_counter()
times = []
for tok in generate_stream(model, ids, max_new_tokens=new):
    times.append(time.perf_counter())
prefill_ms = (times[0] - t0) * 1e3
decode = np.diff(np.array(times)) * 1e3
print(f"b{b} s{s}: prefill {prefill_ms:.1f} ms | decode p50 "
      f"{np.percentile(decode, 50):.1f} ms/tok, p90 "
      f"{np.percentile(decode, 90):.1f} | throughput "
      f"{b * len(decode) / (times[-1] - times[0]):.1f} tok/s "
      f"({len(decode)} steps)")

# device-side block decode (r4): tokens_per_fetch=N runs N decode steps
# in ONE lax.while_loop program per host round trip, so the tunnel RTT
# amortizes N-fold and the number finally reflects chip decode rate
# (VERDICT r3 item 3 — the per-token numbers above characterize the
# tunnel, not the chip).
for tpf in (32,):
    # warm the block program
    for _ in generate_stream(model, ids, max_new_tokens=new,
                             tokens_per_fetch=tpf):
        pass
    t0 = time.perf_counter()
    n = 0
    for tok in generate_stream(model, ids, max_new_tokens=new,
                               tokens_per_fetch=tpf):
        n += 1
    dt = time.perf_counter() - t0
    # the first token comes from prefill; the block path covers the rest
    print(f"b{b} s{s} tokens_per_fetch={tpf}: {b * n / dt:.1f} tok/s "
          f"end-to-end incl prefill | {(dt) * 1e3 / n:.2f} ms/tok avg "
          f"({n} tokens)")
