"""int8-vs-bf16 inference benchmark on the attached TPU chip (VERDICT r2
item 3 evidence). Run single-process under the default (axon) env:
    python tools/quant_bench.py
Measures a 12-layer/1024-hidden Llama forward, bf16 weights vs PTQ
int8 (W8A8: s8 x s8 -> s32 dot_general + fused dequant epilogue).
Round-3 measurement (v5e 16G, b4 s1024): bf16 40.6 ms, int8 35.0 ms
= 1.16x. Matmul micro (4096^3, chained): bf16 118.6 TF/s, int8
128.3 TOP/s = 1.08x."""
import os
import sys
import time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np
import paddle_tpu as paddle
from paddle_tpu.models import LlamaForCausalLM
from paddle_tpu.models.llama import tiny_llama_config
from paddle_tpu.quantization import (PTQ, QuantConfig, HistObserver,
                                     AbsMaxChannelWiseWeightObserver,
                                     QuantizedLinear, QuantizedConv2D)

import paddle_tpu.tensor as T


def _bench_conv():
    """int8 conv stack vs bf16 (QuantizedConv2D W8A8 path): 8x
    Conv2D(256,256,3x3) at 56x56 b8 NCHW — ~237 GFLOP/forward."""
    from paddle_tpu import nn
    paddle.seed(0)
    layers = []
    for _ in range(8):
        layers += [nn.Conv2D(256, 256, 3, padding=1), nn.ReLU()]
    model = nn.Sequential(*layers)
    model.eval()
    model = paddle.amp.decorate(models=model, level="O2", dtype="bfloat16")
    rng = np.random.RandomState(0)
    calib = [rng.randn(2, 256, 56, 56).astype("float32") * 0.5
             for _ in range(3)]
    q = PTQ(QuantConfig(activation=HistObserver(percent=0.9999),
                        weight=AbsMaxChannelWiseWeightObserver()))
    qmodel = q.quantize(model)
    for c in calib:
        qmodel(paddle.cast(paddle.to_tensor(c), "bfloat16"))
    int8_model = q.convert(qmodel, execute="int8")
    n8 = sum(isinstance(l, QuantizedConv2D) for l in int8_model.sublayers())
    print("int8 convs:", n8, flush=True)
    x = rng.randn(8, 256, 56, 56).astype("float32") * 0.5

    def bench(m, reps=20):
        sf = paddle.jit.to_static(m)
        xt = paddle.cast(paddle.to_tensor(x), "bfloat16")
        with paddle.no_grad():
            first = sf(xt).numpy()
            float(T.sum(sf(xt)))
            t0 = time.perf_counter()
            for _ in range(reps):
                out = sf(xt)
            float(T.sum(out))
        return (time.perf_counter() - t0) / reps, first

    tb, rf = bench(model)
    ti, ri = bench(int8_model)
    rel = np.abs(ri.astype(np.float32) - rf.astype(np.float32)).mean() \
        / (np.abs(rf.astype(np.float32)).mean() or 1.0)
    gflop = 2 * 8 * 8 * 56 * 56 * 256 * 256 * 9 / 1e9
    print(f"bf16 conv fwd: {tb*1e3:.2f} ms ({gflop/tb/1e3:.1f} TF/s) | "
          f"int8: {ti*1e3:.2f} ms ({gflop/ti/1e3:.1f} TOP/s) | "
          f"speedup {tb/ti:.2f}x | rel-err {rel:.4f}")


if len(sys.argv) > 1 and sys.argv[1] == "conv":
    _bench_conv()
    sys.exit(0)

paddle.seed(0)
cfg = tiny_llama_config(num_hidden_layers=12, hidden_size=1024,
                        intermediate_size=2816, num_attention_heads=16,
                        num_key_value_heads=8, vocab_size=16384,
                        seq_length=1024)
model = LlamaForCausalLM(cfg)
model.eval()
# bf16 baseline (the deployment dtype)
model = paddle.amp.decorate(models=model, level="O2", dtype="bfloat16")
rng = np.random.RandomState(0)
calib = [rng.randint(0, cfg.vocab_size, (2, 128)).astype("int32")
         for _ in range(3)]
q = PTQ(QuantConfig(activation=HistObserver(percent=0.9999),
                    weight=AbsMaxChannelWiseWeightObserver()))
qmodel = q.quantize(model)
for ids in calib:
    qmodel(paddle.to_tensor(ids))
int8_model = q.convert(qmodel, execute="int8")
del qmodel
n8 = sum(isinstance(l, QuantizedLinear) for l in int8_model.sublayers())
print("int8 linears:", n8, flush=True)

x = rng.randint(0, cfg.vocab_size, (4, 1024)).astype("int32")

import paddle_tpu.tensor as T

def bench(m, reps=15):
    sf = paddle.jit.to_static(m)
    xt = paddle.to_tensor(x)
    with paddle.no_grad():
        first = sf(xt).numpy()         # sync + compile (fetch once)
        float(T.sum(sf(xt)))           # warm the scalar-fetch path
        t0 = time.perf_counter()
        for _ in range(reps):
            out = sf(xt)
        float(T.sum(out))              # sync on a scalar, not 268MB
    return (time.perf_counter() - t0) / reps, first

tb, lf = bench(model)
ti, li = bench(int8_model)
agree = (li.argmax(-1) == lf.argmax(-1)).mean()
print(f"bf16 forward: {tb*1e3:.2f} ms | int8 forward: {ti*1e3:.2f} ms | "
      f"speedup {tb/ti:.2f}x | top1-agree {agree:.3f}")
