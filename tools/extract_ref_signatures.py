"""Extract reference API signatures into tests/data/ref_signatures.json
(VERDICT r3 item 10: name parity alone lets defaults/kwarg semantics
drift — record the reference's ~100 highest-traffic signatures and gate
on them).

The reference package cannot be imported (its compiled libpaddle is not
built here), so signatures are read from SOURCE with ast: for functions
the module-level `def`, for classes the `__init__`. Defaults are kept
only when they are literals (ast.literal_eval) — complex defaults are
recorded as the sentinel "<expr>" and only name/order is checked.

Run: python tools/extract_ref_signatures.py   (rewrites the JSON)
"""
from __future__ import annotations

import ast
import json
import os

REF = "/root/reference/python/paddle"
OUT = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tests", "data", "ref_signatures.json")

# (our dotted path, kind, reference file, def name)
# kind: "fn" = module-level function, "cls" = class __init__
APIS = [
    # tensor creation
    ("paddle.to_tensor", "fn", "tensor/creation.py", "to_tensor"),
    ("paddle.zeros", "fn", "tensor/creation.py", "zeros"),
    ("paddle.ones", "fn", "tensor/creation.py", "ones"),
    ("paddle.full", "fn", "tensor/creation.py", "full"),
    ("paddle.arange", "fn", "tensor/creation.py", "arange"),
    ("paddle.linspace", "fn", "tensor/creation.py", "linspace"),
    ("paddle.eye", "fn", "tensor/creation.py", "eye"),
    ("paddle.full_like", "fn", "tensor/creation.py", "full_like"),
    ("paddle.zeros_like", "fn", "tensor/creation.py", "zeros_like"),
    ("paddle.ones_like", "fn", "tensor/creation.py", "ones_like"),
    ("paddle.tril", "fn", "tensor/creation.py", "tril"),
    ("paddle.triu", "fn", "tensor/creation.py", "triu"),
    # math
    ("paddle.add", "fn", "tensor/math.py", "add"),
    ("paddle.subtract", "fn", "tensor/math.py", "subtract"),
    ("paddle.multiply", "fn", "tensor/math.py", "multiply"),
    ("paddle.divide", "fn", "tensor/math.py", "divide"),
    ("paddle.pow", "fn", "tensor/math.py", "pow"),
    ("paddle.exp", "fn", "tensor/ops.py", "exp"),
    ("paddle.sqrt", "fn", "tensor/ops.py", "sqrt"),
    ("paddle.abs", "fn", "tensor/ops.py", "abs"),
    ("paddle.sum", "fn", "tensor/math.py", "sum"),
    ("paddle.mean", "fn", "tensor/stat.py", "mean"),
    ("paddle.max", "fn", "tensor/math.py", "max"),
    ("paddle.min", "fn", "tensor/math.py", "min"),
    ("paddle.cumsum", "fn", "tensor/math.py", "cumsum"),
    ("paddle.clip", "fn", "tensor/math.py", "clip"),
    ("paddle.std", "fn", "tensor/stat.py", "std"),
    ("paddle.var", "fn", "tensor/stat.py", "var"),
    ("paddle.log", "fn", "tensor/math.py", "log"),
    ("paddle.floor", "fn", "tensor/ops.py", "floor"),
    ("paddle.ceil", "fn", "tensor/ops.py", "ceil"),
    # linalg
    ("paddle.matmul", "fn", "tensor/linalg.py", "matmul"),
    ("paddle.dot", "fn", "tensor/linalg.py", "dot"),
    ("paddle.bmm", "fn", "tensor/linalg.py", "bmm"),
    ("paddle.einsum", "fn", "tensor/einsum.py", "einsum"),
    ("paddle.norm", "fn", "tensor/linalg.py", "norm"),
    ("paddle.t", "fn", "tensor/linalg.py", "t"),
    # manipulation
    ("paddle.concat", "fn", "tensor/manipulation.py", "concat"),
    ("paddle.split", "fn", "tensor/manipulation.py", "split"),
    ("paddle.reshape", "fn", "tensor/manipulation.py", "reshape"),
    ("paddle.squeeze", "fn", "tensor/manipulation.py", "squeeze"),
    ("paddle.unsqueeze", "fn", "tensor/manipulation.py", "unsqueeze"),
    ("paddle.stack", "fn", "tensor/manipulation.py", "stack"),
    ("paddle.gather", "fn", "tensor/manipulation.py", "gather"),
    ("paddle.tile", "fn", "tensor/manipulation.py", "tile"),
    ("paddle.flatten", "fn", "tensor/manipulation.py", "flatten"),
    ("paddle.roll", "fn", "tensor/manipulation.py", "roll"),
    ("paddle.flip", "fn", "tensor/manipulation.py", "flip"),
    ("paddle.chunk", "fn", "tensor/manipulation.py", "chunk"),
    ("paddle.transpose", "fn", "tensor/linalg.py", "transpose"),
    ("paddle.cast", "fn", "tensor/manipulation.py", "cast"),
    # search / sort
    ("paddle.argmax", "fn", "tensor/search.py", "argmax"),
    ("paddle.argmin", "fn", "tensor/search.py", "argmin"),
    ("paddle.argsort", "fn", "tensor/search.py", "argsort"),
    ("paddle.sort", "fn", "tensor/search.py", "sort"),
    ("paddle.topk", "fn", "tensor/search.py", "topk"),
    ("paddle.where", "fn", "tensor/search.py", "where"),
    ("paddle.index_select", "fn", "tensor/search.py", "index_select"),
    ("paddle.nonzero", "fn", "tensor/search.py", "nonzero"),
    ("paddle.masked_select", "fn", "tensor/search.py", "masked_select"),
    # random
    ("paddle.rand", "fn", "tensor/random.py", "rand"),
    ("paddle.randn", "fn", "tensor/random.py", "randn"),
    ("paddle.randint", "fn", "tensor/random.py", "randint"),
    ("paddle.uniform", "fn", "tensor/random.py", "uniform"),
    ("paddle.normal", "fn", "tensor/random.py", "normal"),
    ("paddle.multinomial", "fn", "tensor/random.py", "multinomial"),
    ("paddle.randperm", "fn", "tensor/random.py", "randperm"),
    # nn.functional
    ("paddle.nn.functional.relu", "fn", "nn/functional/activation.py",
     "relu"),
    ("paddle.nn.functional.gelu", "fn", "nn/functional/activation.py",
     "gelu"),
    ("paddle.nn.functional.softmax", "fn",
     "nn/functional/activation.py", "softmax"),
    ("paddle.nn.functional.log_softmax", "fn",
     "nn/functional/activation.py", "log_softmax"),
    ("paddle.nn.functional.silu", "fn", "nn/functional/activation.py",
     "silu"),
    ("paddle.nn.functional.leaky_relu", "fn",
     "nn/functional/activation.py", "leaky_relu"),
    ("paddle.nn.functional.cross_entropy", "fn",
     "nn/functional/loss.py", "cross_entropy"),
    ("paddle.nn.functional.mse_loss", "fn", "nn/functional/loss.py",
     "mse_loss"),
    ("paddle.nn.functional.l1_loss", "fn", "nn/functional/loss.py",
     "l1_loss"),
    ("paddle.nn.functional.nll_loss", "fn", "nn/functional/loss.py",
     "nll_loss"),
    ("paddle.nn.functional.binary_cross_entropy", "fn",
     "nn/functional/loss.py", "binary_cross_entropy"),
    ("paddle.nn.functional.smooth_l1_loss", "fn",
     "nn/functional/loss.py", "smooth_l1_loss"),
    ("paddle.nn.functional.kl_div", "fn", "nn/functional/loss.py",
     "kl_div"),
    ("paddle.nn.functional.linear", "fn", "nn/functional/common.py",
     "linear"),
    ("paddle.nn.functional.dropout", "fn", "nn/functional/common.py",
     "dropout"),
    ("paddle.nn.functional.pad", "fn", "nn/functional/common.py",
     "pad"),
    ("paddle.nn.functional.interpolate", "fn",
     "nn/functional/common.py", "interpolate"),
    ("paddle.nn.functional.embedding", "fn", "nn/functional/input.py",
     "embedding"),
    ("paddle.nn.functional.conv2d", "fn", "nn/functional/conv.py",
     "conv2d"),
    ("paddle.nn.functional.conv1d", "fn", "nn/functional/conv.py",
     "conv1d"),
    ("paddle.nn.functional.conv2d_transpose", "fn",
     "nn/functional/conv.py", "conv2d_transpose"),
    ("paddle.nn.functional.layer_norm", "fn", "nn/functional/norm.py",
     "layer_norm"),
    ("paddle.nn.functional.batch_norm", "fn", "nn/functional/norm.py",
     "batch_norm"),
    ("paddle.nn.functional.normalize", "fn", "nn/functional/norm.py",
     "normalize"),
    ("paddle.nn.functional.avg_pool2d", "fn",
     "nn/functional/pooling.py", "avg_pool2d"),
    ("paddle.nn.functional.max_pool2d", "fn",
     "nn/functional/pooling.py", "max_pool2d"),
    ("paddle.nn.functional.adaptive_avg_pool2d", "fn",
     "nn/functional/pooling.py", "adaptive_avg_pool2d"),
    ("paddle.nn.functional.scaled_dot_product_attention", "fn",
     "nn/functional/flash_attention.py", "scaled_dot_product_attention"),
    ("paddle.nn.functional.sigmoid", "fn", "tensor/ops.py",
     "sigmoid"),
    # nn layers
    ("paddle.nn.Linear", "cls", "nn/layer/common.py", "Linear"),
    ("paddle.nn.Embedding", "cls", "nn/layer/common.py", "Embedding"),
    ("paddle.nn.Dropout", "cls", "nn/layer/common.py", "Dropout"),
    ("paddle.nn.Conv2D", "cls", "nn/layer/conv.py", "Conv2D"),
    ("paddle.nn.LayerNorm", "cls", "nn/layer/norm.py", "LayerNorm"),
    ("paddle.nn.BatchNorm2D", "cls", "nn/layer/norm.py", "BatchNorm2D"),
    ("paddle.nn.MultiHeadAttention", "cls", "nn/layer/transformer.py",
     "MultiHeadAttention"),
    ("paddle.nn.TransformerEncoderLayer", "cls",
     "nn/layer/transformer.py", "TransformerEncoderLayer"),
    ("paddle.nn.CrossEntropyLoss", "cls", "nn/layer/loss.py",
     "CrossEntropyLoss"),
    ("paddle.nn.MSELoss", "cls", "nn/layer/loss.py", "MSELoss"),
    ("paddle.nn.LSTM", "cls", "nn/layer/rnn.py", "LSTM"),
    ("paddle.nn.GRU", "cls", "nn/layer/rnn.py", "GRU"),
    # optimizers + lr
    ("paddle.optimizer.SGD", "cls", "optimizer/sgd.py", "SGD"),
    ("paddle.optimizer.Momentum", "cls", "optimizer/momentum.py",
     "Momentum"),
    ("paddle.optimizer.Adam", "cls", "optimizer/adam.py", "Adam"),
    ("paddle.optimizer.AdamW", "cls", "optimizer/adamw.py", "AdamW"),
    ("paddle.optimizer.lr.CosineAnnealingDecay", "cls",
     "optimizer/lr.py", "CosineAnnealingDecay"),
    ("paddle.optimizer.lr.LinearWarmup", "cls", "optimizer/lr.py",
     "LinearWarmup"),
    # io
    ("paddle.io.DataLoader", "cls", "io/reader.py", "DataLoader"),
    # distributed eager API
    ("paddle.distributed.all_reduce", "fn",
     "distributed/communication/all_reduce.py", "all_reduce"),
    ("paddle.distributed.all_gather", "fn",
     "distributed/communication/all_gather.py", "all_gather"),
    ("paddle.distributed.broadcast", "fn",
     "distributed/communication/broadcast.py", "broadcast"),
    ("paddle.distributed.reduce_scatter", "fn",
     "distributed/communication/reduce_scatter.py", "reduce_scatter"),
    ("paddle.distributed.shard_tensor", "fn",
     "distributed/auto_parallel/api.py", "shard_tensor"),
    ("paddle.distributed.reshard", "fn",
     "distributed/auto_parallel/api.py", "reshard"),
    # round-4 extension: second tranche (comm, amp, jit, lr, layers)
    ("paddle.distributed.all_to_all", "fn",
     "distributed/communication/all_to_all.py", "alltoall"),
    ("paddle.distributed.scatter", "fn",
     "distributed/communication/scatter.py", "scatter"),
    ("paddle.distributed.reduce", "fn",
     "distributed/communication/reduce.py", "reduce"),
    ("paddle.distributed.send", "fn",
     "distributed/communication/send.py", "send"),
    ("paddle.distributed.recv", "fn",
     "distributed/communication/recv.py", "recv"),
    ("paddle.distributed.barrier", "fn",
     "distributed/communication/group.py", "barrier"),
    ("paddle.amp.auto_cast", "fn", "amp/auto_cast.py", "auto_cast"),
    ("paddle.amp.decorate", "fn", "amp/auto_cast.py", "decorate"),
    ("paddle.amp.GradScaler", "cls", "amp/grad_scaler.py", "GradScaler"),
    ("paddle.optimizer.lr.StepDecay", "cls", "optimizer/lr.py",
     "StepDecay"),
    ("paddle.optimizer.lr.MultiStepDecay", "cls", "optimizer/lr.py",
     "MultiStepDecay"),
    ("paddle.optimizer.lr.ExponentialDecay", "cls", "optimizer/lr.py",
     "ExponentialDecay"),
    ("paddle.optimizer.lr.NoamDecay", "cls", "optimizer/lr.py",
     "NoamDecay"),
    ("paddle.optimizer.lr.PolynomialDecay", "cls", "optimizer/lr.py",
     "PolynomialDecay"),
    ("paddle.optimizer.lr.ReduceOnPlateau", "cls", "optimizer/lr.py",
     "ReduceOnPlateau"),
    ("paddle.nn.ReLU", "cls", "nn/layer/activation.py", "ReLU"),
    ("paddle.nn.Softmax", "cls", "nn/layer/activation.py", "Softmax"),
    ("paddle.nn.GroupNorm", "cls", "nn/layer/norm.py", "GroupNorm"),
    ("paddle.nn.InstanceNorm2D", "cls", "nn/layer/norm.py",
     "InstanceNorm2D"),
    ("paddle.nn.Conv1D", "cls", "nn/layer/conv.py", "Conv1D"),
    ("paddle.nn.Conv3D", "cls", "nn/layer/conv.py", "Conv3D"),
    ("paddle.nn.Conv2DTranspose", "cls", "nn/layer/conv.py",
     "Conv2DTranspose"),
    ("paddle.nn.AvgPool2D", "cls", "nn/layer/pooling.py", "AvgPool2D"),
    ("paddle.nn.MaxPool2D", "cls", "nn/layer/pooling.py", "MaxPool2D"),
    ("paddle.nn.Flatten", "cls", "nn/layer/common.py", "Flatten"),
    ("paddle.nn.Upsample", "cls", "nn/layer/common.py", "Upsample"),
    ("paddle.nn.GRUCell", "cls", "nn/layer/rnn.py", "GRUCell"),
    ("paddle.nn.LSTMCell", "cls", "nn/layer/rnn.py", "LSTMCell"),
    ("paddle.nn.functional.one_hot", "fn", "nn/functional/input.py",
     "one_hot"),
    ("paddle.nn.functional.label_smooth", "fn",
     "nn/functional/common.py", "label_smooth"),
    ("paddle.nn.functional.ctc_loss", "fn", "nn/functional/loss.py",
     "ctc_loss"),
    ("paddle.nn.functional.margin_ranking_loss", "fn",
     "nn/functional/loss.py", "margin_ranking_loss"),
    ("paddle.nn.functional.triplet_margin_loss", "fn",
     "nn/functional/loss.py", "triplet_margin_loss"),
    ("paddle.nn.functional.cosine_embedding_loss", "fn",
     "nn/functional/loss.py", "cosine_embedding_loss"),
    ("paddle.nn.functional.unfold", "fn", "nn/functional/common.py",
     "unfold"),
    ("paddle.nn.functional.grid_sample", "fn",
     "nn/functional/vision.py", "grid_sample"),
    ("paddle.nn.functional.pixel_shuffle", "fn",
     "nn/functional/vision.py", "pixel_shuffle"),
    ("paddle.scatter", "fn", "tensor/manipulation.py", "scatter"),
    ("paddle.put_along_axis", "fn", "tensor/manipulation.py",
     "put_along_axis"),
    ("paddle.take_along_axis", "fn", "tensor/manipulation.py",
     "take_along_axis"),
    ("paddle.diag", "fn", "tensor/creation.py", "diag"),
    ("paddle.kron", "fn", "tensor/math.py", "kron"),
    ("paddle.trace", "fn", "tensor/math.py", "trace"),
    ("paddle.logsumexp", "fn", "tensor/math.py", "logsumexp"),
    ("paddle.nanmean", "fn", "tensor/math.py", "nanmean"),
    ("paddle.quantile", "fn", "tensor/stat.py", "quantile"),
    ("paddle.bucketize", "fn", "tensor/search.py", "bucketize"),
    ("paddle.searchsorted", "fn", "tensor/search.py", "searchsorted"),
    ("paddle.histogram", "fn", "tensor/linalg.py", "histogram"),
    ("paddle.unique", "fn", "tensor/manipulation.py", "unique"),
    ("paddle.repeat_interleave", "fn", "tensor/manipulation.py",
     "repeat_interleave"),
    ("paddle.vision.ops.roi_align", "fn", "vision/ops.py", "roi_align"),
    ("paddle.vision.ops.nms", "fn", "vision/ops.py", "nms"),
]



def _sig_of(node: ast.FunctionDef):
    """-> list of [name, default_repr|None]; *args/**kwargs noted."""
    a = node.args
    params = []
    pos = list(a.posonlyargs) + list(a.args)
    defaults = [None] * (len(pos) - len(a.defaults)) + list(a.defaults)
    for arg, d in zip(pos, defaults):
        params.append([arg.arg, _default_repr(d)])
    if a.vararg:
        params.append(["*" + a.vararg.arg, None])
    for arg, d in zip(a.kwonlyargs, a.kw_defaults):
        params.append([arg.arg, _default_repr(d)])
    if a.kwarg:
        params.append(["**" + a.kwarg.arg, None])
    return params


def _default_repr(d):
    if d is None:
        return None
    try:
        return repr(ast.literal_eval(d))
    except (ValueError, SyntaxError):
        return "<expr>"


def extract():
    out = {}
    for ours, kind, relfile, name in APIS:
        path = os.path.join(REF, relfile)
        tree = ast.parse(open(path).read())
        node = None
        if kind == "fn":
            for n in tree.body:
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                        and n.name == name:
                    node = n
                    break
        else:
            classes = {n.name: n for n in tree.body
                       if isinstance(n, ast.ClassDef)}

            def init_of(cname, depth=0):
                c = classes.get(cname)
                if c is None or depth > 4:
                    return None
                for m in c.body:
                    if isinstance(m, ast.FunctionDef) \
                            and m.name == "__init__":
                        return m
                # inherited __init__: walk same-module bases
                for b in c.bases:
                    if isinstance(b, ast.Name):
                        got = init_of(b.id, depth + 1)
                        if got is not None:
                            return got
                return None

            node = init_of(name)
        if node is None:
            # reference tensor/ops.py generates simple unary ops via
            # generate_activation_fn(op) with the uniform signature
            # (x, name=None) (reference tensor/ops.py:83)
            src = open(path).read()
            if f"'{name}'" in src and "generate_activation_fn" in src:
                out[ours] = {"kind": "fn", "ref": f"{relfile}:generated",
                             "params": [["x", None], ["name", "None"]]}
                continue
            raise LookupError(f"{name} not found in {relfile}")
        params = _sig_of(node)
        if kind == "cls" and params and params[0][0] == "self":
            params = params[1:]
        out[ours] = {"kind": kind, "ref": f"{relfile}:{node.lineno}",
                     "params": params}
    os.makedirs(os.path.dirname(OUT), exist_ok=True)
    with open(OUT, "w") as f:
        json.dump(out, f, indent=1, sort_keys=True)
    print(f"wrote {len(out)} signatures to {OUT}")


if __name__ == "__main__":
    extract()
