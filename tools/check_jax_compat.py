#!/usr/bin/env python
"""Fail CI when version-fragile jax imports sneak into paddle_tpu/.

THIN SHIM: the scanner now lives in the unified static-analysis
framework as the `jax-compat` pass (tools/analyze/passes/jax_compat.py)
and runs with the full suite via `python -m tools.analyze`. This CLI
(and its `scan(root)` surface, used by tests/test_jax_compat_tool.py)
is kept so nothing downstream breaks.

Usage: python tools/check_jax_compat.py [root]
Exit 0 = clean, 1 = offending lines found.
"""
from __future__ import annotations

import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

from tools.analyze.passes.jax_compat import (  # noqa: E402,F401
    ALLOWED, FRAGILE, scan)


def main(argv):
    root = argv[1] if len(argv) > 1 else _ROOT
    bad = list(scan(root))
    if not bad:
        print("check_jax_compat: clean")
        return 0
    print(f"check_jax_compat: {len(bad)} version-fragile jax "
          "import(s):", file=sys.stderr)
    for rel, no, line, why in bad:
        print(f"  {rel}:{no}: {line}\n      -> {why}", file=sys.stderr)
    return 1


if __name__ == "__main__":
    sys.exit(main(sys.argv))
