#!/usr/bin/env python
"""Fail CI when version-fragile jax imports sneak into paddle_tpu/.

`from jax import shard_map` only exists on jax >= 0.6 and broke
collection of 10 test files on 0.4.37; `jax.shard_map(...)` attribute
access breaks the same way at call time. The sanctioned spelling is
`from paddle_tpu.core.jax_compat import shard_map` (which also
translates the check_vma/check_rep kwarg rename). This checker greps
the package for the fragile spellings and prints each offending line.

Usage: python tools/check_jax_compat.py [root]
Exit 0 = clean, 1 = offending lines found.

Wired into the tier-1 flow via tests/test_jax_compat_tool.py.
"""
from __future__ import annotations

import os
import re
import sys

# (pattern, why). Docstrings/comments are excluded by stripping `#`
# trails and skipping lines without code; prose mentions inside
# docstrings are tolerated (they can't break an import).
FRAGILE = [
    (re.compile(r"^\s*from\s+jax\s+import\s+(?:\([^)]*\bshard_map\b"
                r"|.*\bshard_map\b)"),
     "`from jax import shard_map` needs jax>=0.6; import it from "
     "paddle_tpu.core.jax_compat instead"),
    (re.compile(r"\bjax\.shard_map\s*\("),
     "`jax.shard_map(...)` needs jax>=0.6; use "
     "paddle_tpu.core.jax_compat.shard_map"),
    (re.compile(r"^\s*from\s+jax\.experimental\.shard_map\s+import"),
     "import shard_map via paddle_tpu.core.jax_compat (handles the "
     "check_rep->check_vma rename), not jax.experimental directly"),
    (re.compile(r"\bjax\.lax\.axis_size\s*\("),
     "`jax.lax.axis_size` does not exist on jax 0.4.x; use "
     "paddle_tpu.core.jax_compat.axis_size"),
]

# the one module allowed to touch the real locations
ALLOWED = {os.path.join("paddle_tpu", "core", "jax_compat.py")}


def _strip(line: str, open_q: str | None):
    """One stateful pass per line: returns (code, new_open_q) with
    comment trails and ALL string-literal contents removed. `open_q` is
    the delimiter of a still-open triple-quoted string from earlier
    lines (None when outside). Tracking strings and comments together
    is what keeps a stray triple-quote inside a COMMENT from hiding the
    rest of the file from the scan."""
    out = []
    i = 0
    while i < len(line):
        if open_q:
            j = line.find(open_q, i)
            if j < 0:
                return "".join(out), open_q     # string spans the line
            i = j + len(open_q)
            open_q = None
            continue
        if line.startswith('"""', i) or line.startswith("'''", i):
            open_q = line[i:i + 3]
            i += 3
            continue
        ch = line[i]
        if ch in "\"'":
            j = line.find(ch, i + 1)
            if j < 0:               # unterminated/escaped: drop the rest
                return "".join(out), None
            i = j + 1
            continue
        if ch == "#":
            return "".join(out), None
        out.append(ch)
        i += 1
    return "".join(out), open_q


def scan(root: str):
    """Yield (relpath, lineno, line, why) for every fragile use."""
    pkg = os.path.join(root, "paddle_tpu")
    for dirpath, _dirnames, filenames in os.walk(pkg):
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, root)
            if rel in ALLOWED:
                continue
            try:
                with open(path, encoding="utf-8") as f:
                    lines = f.read().splitlines()
            except OSError:
                continue
            open_q = None
            for no, line in enumerate(lines, 1):
                code, open_q = _strip(line, open_q)
                for pat, why in FRAGILE:
                    if pat.search(code):
                        yield rel, no, line.rstrip(), why
                        break


def main(argv):
    root = argv[1] if len(argv) > 1 else \
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    bad = list(scan(root))
    if not bad:
        print("check_jax_compat: clean")
        return 0
    print(f"check_jax_compat: {len(bad)} version-fragile jax "
          "import(s):", file=sys.stderr)
    for rel, no, line, why in bad:
        print(f"  {rel}:{no}: {line}\n      -> {why}", file=sys.stderr)
    return 1


if __name__ == "__main__":
    sys.exit(main(sys.argv))
