"""Pretty-print a crash flight-recorder bundle
(paddle_tpu/observability/fleet.py `record_crash`).

Usage:
    python -m tools.obs_dump <bundle-dir>        one bundle
    python -m tools.obs_dump <flight-dir>        newest bundle inside
    python -m tools.obs_dump <bundle-dir> --json the parsed dict

A bundle is a directory named ``flight-<ms>-<seq>-<reason>`` holding
manifest.json / metrics.json / trace.json / requests.json /
fleet.json / traceback.txt. `load()` parses it into one dict (the
programmatic surface tests round-trip through); `render()` produces
the human summary: what died, the last cross-rank fleet view with
straggler flags, the in-flight requests, headline counters, and the
traceback.

Stdlib-only; never imports paddle_tpu or jax — a bundle must be
readable on a workstation with nothing installed.
"""
from __future__ import annotations

import json
import os
import sys

BUNDLE_FILES = ("manifest.json", "metrics.json", "trace.json",
                "requests.json", "fleet.json", "traceback.txt")


def is_bundle(path: str) -> bool:
    return os.path.isfile(os.path.join(path, "manifest.json"))


def resolve(path: str) -> str:
    """`path` itself when it is a bundle, else the newest
    ``flight-*`` bundle directory inside it."""
    if is_bundle(path):
        return path
    if os.path.isdir(path):
        cands = sorted(n for n in os.listdir(path)
                       if n.startswith("flight-"))
        if cands:
            return os.path.join(path, cands[-1])
    raise FileNotFoundError(
        f"{path!r} is neither a flight-recorder bundle (no "
        "manifest.json) nor a directory containing flight-* bundles")


def load(path: str) -> dict:
    """Parse every bundle artifact into one dict keyed by artifact
    stem (+ "path"). Missing or unparseable artifacts surface as
    {"error": ...} under their key rather than failing the whole load
    — half the point of a crash bundle is surviving imperfect dumps."""
    path = resolve(path)
    out = {"path": path}
    for name in BUNDLE_FILES:
        stem = name.rsplit(".", 1)[0]
        fp = os.path.join(path, name)
        try:
            with open(fp) as f:
                out[stem] = (f.read() if name.endswith(".txt")
                             else json.load(f))
        except (OSError, ValueError) as e:
            out[stem] = {"error": f"{type(e).__name__}: {e}"}
    return out


def _counter_lines(metrics: dict) -> list:
    lines = []
    for name, fam in sorted(metrics.items()):
        if not isinstance(fam, dict) or fam.get("kind") != "counter":
            continue
        for s in fam.get("series", []):
            label = ",".join(f"{k}={v}"
                             for k, v in sorted(s["labels"].items()))
            suffix = f"{{{label}}}" if label else ""
            lines.append(f"  {name}{suffix} = {s['value']}")
    return lines


def _fleet_lines(fleet: dict) -> list:
    if not isinstance(fleet, dict) or not fleet.get("available"):
        return ["  (no fleet view recorded)"]
    view = fleet.get("view") or {}
    summary = view.get("summary", {})
    lines = [f"  world_size={view.get('world_size')} "
             f"present={summary.get('present')} "
             f"stale={summary.get('stale_ranks')} "
             f"step_skew={summary.get('step_skew')} "
             f"step_lag={summary.get('step_lag')} "
             f"stragglers={summary.get('stragglers')}"]
    for row in view.get("ranks", []):
        mark = " <-- STRAGGLER" if row.get("straggler") else ""
        lines.append(
            f"  rank {row.get('rank')}: present={row.get('present')} "
            f"step={row.get('step')} lag={row.get('lag')} "
            f"age_s={row.get('age_s')} "
            f"tok/s={row.get('tokens_per_sec')}{mark}")
    return lines


def _sentry_lines(manifest: dict) -> list:
    """The training-sentry section (bundles dumped by
    distributed/sentry.py carry detector state under
    manifest.extra.sentry); [] when this bundle is not a sentry one."""
    extra = manifest.get("extra")
    s = extra.get("sentry") if isinstance(extra, dict) else None
    if not isinstance(s, dict):
        return []
    rng = s.get("step_range") or ["?", "?"]
    lines = [
        "",
        "sentry:",
        f"  trigger={s.get('trigger')} policy={s.get('policy')} "
        f"at step={s.get('step')} cursor={s.get('cursor')}",
        f"  loss={s.get('loss')} grad_norm={s.get('grad_norm')} "
        f"ewma={s.get('ewma')} sigma={s.get('sigma')} "
        f"zscore={s.get('zscore')}",
        f"  steps_since_good={s.get('steps_since_good')} "
        f"offending step range=[{rng[0]}, {rng[1]}] "
        f"rollbacks_in_window={s.get('rollbacks_in_window')}",
        f"  rollback target: {s.get('rollback_target') or '(none)'}",
    ]
    hist = s.get("history") or []
    if hist:
        lines.append(f"  history (last {len(hist)} steps: step "
                     "cursor loss grad_norm applied):")
        for row in hist[-8:]:
            lines.append("    " + " ".join(str(x) for x in row))
    return lines


def _request_lines(requests: dict) -> list:
    if not isinstance(requests, dict):
        return ["  (unreadable)"]
    rows = requests.get("requests") or []
    if not rows:
        return ["  (none in flight)"]
    return [f"  {r.get('request_id')} stage={r.get('stage')} "
            f"age_s={r.get('age_s')} tokens={r.get('tokens')}"
            for r in rows]


def render(path: str) -> str:
    """The human summary of one bundle."""
    b = load(path)
    man = b.get("manifest") or {}
    exc = man.get("exception")
    trace_doc = b.get("trace") or {}
    n_spans = len(trace_doc.get("traceEvents") or []) \
        if isinstance(trace_doc, dict) else 0
    lines = [
        f"flight-recorder bundle: {b['path']}",
        f"reason: {man.get('reason')}   at {man.get('iso_time')} "
        f"(pid {man.get('pid')} on {man.get('host')})",
        "exception: " + (f"{exc['type']}: {exc['message']}" if exc
                         else "(none recorded)"),
        *_sentry_lines(man),
        "",
        "fleet view (last seen):",
        *_fleet_lines(b.get("fleet")),
        "",
        "in-flight requests:",
        *_request_lines(b.get("requests")),
        "",
        f"spans in trace.json: {n_spans}",
        "counters:",
        *(_counter_lines(b.get("metrics") or {}) or ["  (none)"]),
    ]
    tb = b.get("traceback")
    if isinstance(tb, str) and tb.strip():
        lines += ["", "traceback.txt:", tb.rstrip()]
    return "\n".join(lines)


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    as_json = "--json" in argv
    argv = [a for a in argv if a != "--json"]
    if len(argv) != 1:
        print(__doc__, file=sys.stderr)
        return 2
    try:
        if as_json:
            print(json.dumps(load(argv[0]), indent=1, sort_keys=True,
                             default=str))
        else:
            print(render(argv[0]))
    except FileNotFoundError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
