#!/usr/bin/env python
"""Fail CI when a metric instrumentation site is off-catalogue.

THIN SHIM: the scanner now lives in the unified static-analysis
framework as the `metric-names` pass
(tools/analyze/passes/metric_names.py) and runs with the full suite via
`python -m tools.analyze`. This CLI (and its `scan(root)` / `ALLOWED`
surface, used by tests/test_metric_names_tool.py) is kept so nothing
downstream breaks.

Usage: python tools/check_metric_names.py [root]
Exit 0 = clean, 1 = undocumented or unauditable names found. Stale
catalogue entries (documented but never instrumented) are reported as
a warning without failing — scrape-time-only metrics and mid-migration
names are legitimate.
"""
from __future__ import annotations

import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

from tools.analyze.passes.metric_names import (  # noqa: E402,F401
    ACQUIRERS, ALLOWED, INSTRUMENTS, scan)


def main(argv):
    root = argv[1] if len(argv) > 1 else _ROOT
    violations, seen, catalogue = scan(root)
    if violations:
        print(f"check_metric_names: {len(violations)} off-catalogue "
              "metric site(s):", file=sys.stderr)
        for rel, no, call, why in violations:
            print(f"  {rel}:{no}: {call}\n      -> {why}",
                  file=sys.stderr)
        return 1
    stale = sorted(k for k in catalogue if k not in seen)
    if stale:
        # warn only: a catalogued metric may be recorded through a
        # non-gated path (exporters) or be mid-migration
        print("check_metric_names: warning, catalogue entries with no "
              f"literal call site: {stale}")
    print(f"check_metric_names: clean ({len(seen)} literal name(s) "
          f"across the package, {len(catalogue)} catalogued)")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
