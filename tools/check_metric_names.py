#!/usr/bin/env python
"""Fail CI when a metric instrumentation site is off-catalogue.

`observability/metrics.py` carries METRICS, the closed catalogue of
every metric name (the README's observability table is generated from
the same source of truth). An instrumentation call whose name is not
catalogued would mint a metric invisible to operators reading the
docs — and a non-literal name cannot be audited at all — so this
checker (modeled on tools/check_chaos_points.py) walks paddle_tpu/
and fails if:

  - `inc("name")` / `observe("name", v)` / `set_gauge("name", v)` —
    the instrumentation surface, on the observability module or any
    MetricsRegistry — is called with a name that has no METRICS entry,
    or with a first argument that is not a string literal;
  - `counter("name")` / `gauge("name")` / `histogram("name")` — the
    instrument acquisition surface — is called with a string-literal
    name that has no METRICS entry. Non-literal first arguments are
    NOT flagged for these three (jnp.histogram/np.histogram share the
    method name with array first arguments).

Usage: python tools/check_metric_names.py [root]
Exit 0 = clean, 1 = undocumented or unauditable names found. Stale
catalogue entries (documented but never instrumented) are reported as
a warning without failing — scrape-time-only metrics and mid-migration
names are legitimate.

Wired into the tier-1 flow via tests/test_metric_names_tool.py (the
same pattern as tools/check_chaos_points.py).
"""
from __future__ import annotations

import ast
import importlib.util
import os
import sys

# literal-REQUIRED instrumentation calls
INSTRUMENTS = {"inc", "observe", "set_gauge"}
# literal-checked-when-literal acquisition calls (numpy/jax collide on
# the bare names with array arguments, which must not false-positive)
ACQUIRERS = {"counter", "gauge", "histogram"}

# the registry implementation itself passes `name` variables around;
# same for the module-level helper shims in the package __init__.
# observability/requests.py (the request-tracing SLO instrumentation)
# is deliberately NOT here: its request.* literals are audited like
# any other call site (tests/test_metric_names_tool.py pins that).
ALLOWED = {
    os.path.join("paddle_tpu", "observability", "metrics.py"),
    os.path.join("paddle_tpu", "observability", "__init__.py"),
}


def _load_catalogue(root: str) -> dict:
    path = os.path.join(root, "paddle_tpu", "observability", "metrics.py")
    spec = importlib.util.spec_from_file_location("_metrics_catalogue",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)        # stdlib-only module (no jax)
    return dict(getattr(mod, "METRICS", {}))


def _literal_of(node):
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def scan(root: str):
    """Return (violations, seen_names, catalogue); violations are
    (relpath, lineno, call, problem)."""
    catalogue = _load_catalogue(root)
    pkg = os.path.join(root, "paddle_tpu")
    violations = []
    seen = set()
    for dirpath, _dirnames, filenames in os.walk(pkg):
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, root)
            if rel in ALLOWED:
                continue
            try:
                with open(path, encoding="utf-8") as f:
                    tree = ast.parse(f.read(), filename=rel)
            except (OSError, SyntaxError):
                continue
            for node in ast.walk(tree):
                if not isinstance(node, ast.Call) or not node.args:
                    continue
                func = node.func
                name = (func.attr if isinstance(func, ast.Attribute)
                        else func.id if isinstance(func, ast.Name)
                        else None)
                if name not in INSTRUMENTS and name not in ACQUIRERS:
                    continue
                metric = _literal_of(node.args[0])
                call = f"{name}({ast.unparse(node.args[0])})"
                if metric is None:
                    if name in INSTRUMENTS:
                        violations.append(
                            (rel, node.lineno, call,
                             "metric name is not a string literal — "
                             "cannot be audited against the METRICS "
                             "catalogue"))
                    continue
                seen.add(metric)
                if metric not in catalogue:
                    violations.append(
                        (rel, node.lineno, call,
                         f"metric {metric!r} is not in the METRICS "
                         "catalogue (observability/metrics.py) — "
                         "register it there"))
    return violations, seen, catalogue


def main(argv):
    root = argv[1] if len(argv) > 1 else \
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    violations, seen, catalogue = scan(root)
    if violations:
        print(f"check_metric_names: {len(violations)} off-catalogue "
              "metric site(s):", file=sys.stderr)
        for rel, no, call, why in violations:
            print(f"  {rel}:{no}: {call}\n      -> {why}",
                  file=sys.stderr)
        return 1
    stale = sorted(k for k in catalogue if k not in seen)
    if stale:
        # warn only: a catalogued metric may be recorded through a
        # non-gated path (exporters) or be mid-migration
        print("check_metric_names: warning, catalogue entries with no "
              f"literal call site: {stale}")
    print(f"check_metric_names: clean ({len(seen)} literal name(s) "
          f"across the package, {len(catalogue)} catalogued)")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
