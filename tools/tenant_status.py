"""Render a serving replica's (or router's) per-tenant QoS view.

    python -m tools.tenant_status http://127.0.0.1:8866 [--json]

Fetches `GET /stats` from a `PredictorServer` replica or a
`ReplicaRouter` configured with a `tenancy=` TenantTable and prints
the per-tenant rows — policy knobs (quotas / weight / priority / rate
cap), live in-flight and queued counts, admission/shed totals, and the
engine's decode slot-tick shares — the operator's one-glance answer to
"which tenant is eating the fleet" and "is the noisy neighbor actually
contained". `--json` dumps the raw tenants block instead (for
scripts).

Stdlib-only (no jax, no paddle_tpu import): this runs on any box that
can reach the server.
"""
from __future__ import annotations

import json
import sys
import urllib.request

__all__ = ["fetch", "render", "main"]


def fetch(base_url, timeout=5.0) -> dict:
    """The /stats document from a live server/router."""
    base = base_url.rstrip("/")
    if not base.startswith("http"):
        base = "http://" + base
    with urllib.request.urlopen(base + "/stats",
                                timeout=timeout) as resp:
        return json.loads(resp.read())


def _fmt(v):
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.3g}"
    return str(v)


def render(doc) -> str:
    """The /stats `tenants` block as an aligned table. Tolerates both
    shapes: the serving replica's rows (policy/in_flight/queued/
    engine) and the router's rows (requests/shed/rate_limit). A
    document without a tenants block renders a one-line notice (the
    server has no TenantTable configured)."""
    tenants = doc.get("tenants") if isinstance(doc, dict) else None
    if not isinstance(tenants, dict) or not tenants:
        return "no per-tenant stats (server has no tenancy configured)"
    cols = ["tenant", "inflight", "quota", "queued", "qquota",
            "weight", "prio", "rate", "admitted", "shed", "requests",
            "slot_ticks", "pending"]
    table = [cols]
    for t in sorted(tenants):
        row = tenants[t] if isinstance(tenants[t], dict) else {}
        pol = row.get("policy") or {}
        eng = row.get("engine") or {}
        table.append([
            t,
            _fmt(row.get("in_flight")),
            _fmt(pol.get("max_in_flight")),
            _fmt(row.get("queued")),
            _fmt(pol.get("max_queued")),
            _fmt(pol.get("weight")),
            _fmt(pol.get("priority")),
            _fmt(pol.get("rate_limit", row.get("rate_limit"))),
            _fmt(row.get("admitted")),
            _fmt(row.get("shed")),
            _fmt(row.get("requests")),
            _fmt(eng.get("slot_ticks")),
            _fmt(eng.get("pending")),
        ])
    widths = [max(len(r[i]) for r in table) for i in range(len(cols))]
    lines = ["  ".join(c.ljust(w) for c, w in zip(r, widths)).rstrip()
             for r in table]
    total_shed = sum(r.get("shed", 0) or 0 for r in tenants.values()
                     if isinstance(r, dict))
    lines.append("")
    lines.append(f"tenants: {len(tenants)}; total shed: {total_shed}")
    return "\n".join(lines)


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    as_json = "--json" in argv
    argv = [a for a in argv if a != "--json"]
    if len(argv) != 1:
        print(__doc__.strip().splitlines()[2].strip(), file=sys.stderr)
        return 2
    try:
        doc = fetch(argv[0])
    except Exception as e:      # noqa: BLE001 — CLI boundary: report, don't traceback
        print(f"error: cannot reach server at {argv[0]}: {e!r}",
              file=sys.stderr)
        return 1
    if as_json:
        print(json.dumps(doc.get("tenants") or {}, indent=1,
                         sort_keys=True))
    else:
        print(render(doc))
    return 0


if __name__ == "__main__":
    sys.exit(main())
