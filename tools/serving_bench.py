"""Continuous-batching paged-KV serving benchmark (real TPU chip).

Run single-process under the default (axon) env:
    python tools/serving_bench.py [n_requests] [prompt_len] [new_tokens]

Measures aggregate decode throughput of the PagedKVEngine
(inference/paged.py) serving `n_requests` requests through
`max_slots=8` decode slots — requests join mid-decode as earlier ones
finish, which is the capability the r4 fixed-batch number (380.6 tok/s
aggregate, BASELINE.md "BATCHED serving") could not exercise: there, 8
streams had to start and finish together.

Model = the serving config BASELINE.md's latency table uses
(8L/1024h bf16 Llama). Decode runs steps_per_tick steps per host round
trip (same RTT amortization as tokens_per_fetch=32 in gen_bench).

Protocol: all requests submitted up front (a closed-loop saturation
test); engine drains them; aggregate tok/s = total generated tokens /
wall time after the compile warmup. A heterogeneous variant staggers
budgets so slots retire early and refill mid-decode.
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.inference.paged import PagedKVEngine
from paddle_tpu.models import LlamaForCausalLM
from paddle_tpu.models.llama import tiny_llama_config

n_req = int(sys.argv[1]) if len(sys.argv) > 1 else 16
s = int(sys.argv[2]) if len(sys.argv) > 2 else 512
new = int(sys.argv[3]) if len(sys.argv) > 3 else 64

paddle.seed(0)
cfg = tiny_llama_config(num_hidden_layers=8, hidden_size=1024,
                        intermediate_size=2816, num_attention_heads=16,
                        num_key_value_heads=8, vocab_size=16384,
                        max_position_embeddings=s + new, seq_length=s)
model = LlamaForCausalLM(cfg)
model.eval()
model = paddle.amp.decorate(models=model, level="O2", dtype="bfloat16")

PAGE = 64
pages_per_req = -(-(s + new) // PAGE)
eng = PagedKVEngine(model, max_slots=8, page_size=PAGE,
                    num_pages=8 * pages_per_req + 1,
                    max_pages_per_slot=pages_per_req,
                    steps_per_tick=16)
rng = np.random.RandomState(0)
prompts = [rng.randint(0, cfg.vocab_size, (s,)).astype("int32")
           for _ in range(n_req)]

# warm: compile BOTH prefill widths (single + storm) and the tick
t0 = time.perf_counter()
r = eng.submit(prompts[0], max_new_tokens=new)
eng.step()
print(f"single prefill + tick compiled: {time.perf_counter()-t0:.1f}s",
      flush=True)
eng.run_until_idle()
r.result()
storm = [eng.submit(p, max_new_tokens=2) for p in prompts[:8]]
eng.run_until_idle()              # compiles the batched (bw=8) prefill
for rr in storm:
    rr.result()
print(f"warm (incl. storm prefill) done: {time.perf_counter()-t0:.1f}s",
      flush=True)
warm_pf, warm_tk = eng.stats["prefill_s"], eng.stats["tick_s"]

# measured: saturate 8 slots from a 16-deep queue; finishing requests
# free their slot and the queue refills it mid-decode of the others
t0 = time.perf_counter()
reqs = [eng.submit(p, max_new_tokens=new) for p in prompts]
eng.run_until_idle()
dt = time.perf_counter() - t0
total = sum(len(r.result()) for r in reqs)
pf = eng.stats["prefill_s"] - warm_pf
tk = eng.stats["tick_s"] - warm_tk
print(f"continuous batching: {n_req} reqs x {new} tok (b8 slots, "
      f"s{s}): {total} tokens in {dt:.2f}s = "
      f"{total / dt:.1f} tok/s aggregate | ticks={eng.stats['ticks']} "
      f"prefills={eng.stats['prefills']} | prefill {pf:.2f}s, decode "
      f"ticks {tk:.2f}s -> decode-phase "
      f"{(total - n_req) / tk:.1f} tok/s "
      f"({total - n_req} tick tokens)")

# heterogeneous budgets: half the requests are short (16 tokens), so
# slots retire early and refill mid-decode — the admission-latency
# shape fixed-batch serving cannot express
eng2 = PagedKVEngine(model, max_slots=8, page_size=PAGE,
                     num_pages=8 * pages_per_req + 1,
                     max_pages_per_slot=pages_per_req,
                     steps_per_tick=16)
r0 = eng2.submit(prompts[0], max_new_tokens=new)
eng2.run_until_idle()          # warm this engine's programs
storm2 = [eng2.submit(p, max_new_tokens=2) for p in prompts[:8]]
eng2.run_until_idle()
warm2 = dict(eng2.stats)          # snapshot: report the measured phase only
budgets = [16 if i % 2 else new for i in range(n_req)]
t0 = time.perf_counter()
reqs = [eng2.submit(p, max_new_tokens=m)
        for p, m in zip(prompts, budgets)]
eng2.run_until_idle()
dt = time.perf_counter() - t0
total = sum(len(r.result()) for r in reqs)
print(f"heterogeneous budgets: {total} tokens in {dt:.2f}s = "
      f"{total / dt:.1f} tok/s aggregate | admitted="
      f"{eng2.stats['admitted'] - warm2['admitted']} "
      f"ticks={eng2.stats['ticks'] - warm2['ticks']}")

# overload probe: with a bounded pending queue, a burst beyond the
# bound sheds a typed EngineOverloaded (what the HTTP tier maps to a
# retryable 503) instead of queueing unboundedly
from paddle_tpu.inference.overload import EngineOverloaded
eng2.max_pending = 2
admitted, shed = [], 0
for p in prompts:
    try:
        admitted.append(eng2.submit(p, max_new_tokens=8))
    except EngineOverloaded:
        shed += 1
eng2.run_until_idle()
for r in admitted:
    r.result()
print(f"overload probe (max_pending=2): {len(admitted)} admitted, "
      f"{shed} shed | engine counters: "
      f"overloaded={eng2.stats['overloaded']} "
      f"expired={eng2.stats['expired']}")
