"""Ring / Ulysses context-parallel attention benchmark.

Usage:
  (TPU, default env)  python tools/cp_bench.py tpu   [seq] [heads] [dim]
  (CPU mesh)          JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
                      python tools/cp_bench.py mesh  [seq]

`tpu` mode (VERDICT r4 weak item 5 — the missing perf datapoint):
single-chip degenerate ring attention (mesh {"sp": 1} — the shard_map
plumbing with zero collectives) vs the plain flash kernel at the same
shape. Bar (internal; the reference has no ring attention): ring at
sp=1 within 15% of flash at S=8k.

`mesh` mode: 8 virtual CPU devices, sp=1..8 — checks the ring's wall
time tracks the per-device compute (S/n long Q block x n ring steps =
flat total compute; the collective volume grows with n, so mild growth
is expected; this run gives the scaling curve a number).
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np


def _bench(fn, *args):
    """Per-call timing is unreliable through the axon tunnel (dispatch
    is async and block_until_ready is a proxy no-op) — so: chain N calls
    inside ONE jit program with a data dependency, FETCH a scalar to
    close the chain (the bench.py protocol), and difference two window
    sizes to cancel the constant tunnel RTT."""
    import functools
    import jax
    import jax.numpy as jnp

    @functools.partial(jax.jit, static_argnames=("n",))
    def chained(q, k, v, n):
        def body(qq, _):
            out = fn(qq, k, v)
            return out.astype(qq.dtype), None
        out, _ = jax.lax.scan(body, q, None, length=n)
        return jnp.sum(out.astype(jnp.float32))

    q, k, v = args
    n_lo, n_hi = 8, 40
    float(chained(q, k, v, n_lo))               # compile both
    float(chained(q, k, v, n_hi))

    def window(n):
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            float(chained(q, k, v, n))          # scalar fetch = sync
            best = min(best, time.perf_counter() - t0)
        return best

    return (window(n_hi) - window(n_lo)) / (n_hi - n_lo) * 1e3


def main():
    mode = sys.argv[1] if len(sys.argv) > 1 else "tpu"
    S = int(sys.argv[2]) if len(sys.argv) > 2 else 8192
    H = int(sys.argv[3]) if len(sys.argv) > 3 else 16
    D = int(sys.argv[4]) if len(sys.argv) > 4 else 64

    import jax
    import jax.numpy as jnp
    from paddle_tpu.distributed.context_parallel import (ring_attention,
                                                         ulysses_attention)
    from paddle_tpu.kernels.flash_attention import flash_attention_bhsd

    rng = np.random.RandomState(0)
    if mode == "tpu":
        from paddle_tpu.distributed.mesh import init_mesh
        mesh = init_mesh({"sp": 1})
        q = jnp.asarray(rng.randn(1, S, H, D), jnp.bfloat16)
        k = jnp.asarray(rng.randn(1, S, H, D), jnp.bfloat16)
        v = jnp.asarray(rng.randn(1, S, H, D), jnp.bfloat16)

        flash = lambda a, b, c: jnp.swapaxes(      # noqa: E731
            flash_attention_bhsd(
                jnp.swapaxes(a, 1, 2), jnp.swapaxes(b, 1, 2),
                jnp.swapaxes(c, 1, 2), causal=True), 1, 2)
        ring = lambda a, b, c: ring_attention(     # noqa: E731
            a, b, c, mesh=mesh.jax_mesh, axis="sp", causal=True)
        t_flash = _bench(flash, q, k, v)
        t_ring = _bench(ring, q, k, v)
        print(f"S={S} H={H} D={D} bf16 single chip: flash "
              f"{t_flash:.2f} ms | ring(sp=1 degenerate) {t_ring:.2f} ms "
              f"| ratio {t_ring / t_flash:.3f}")
        uly = lambda a, b, c: ulysses_attention(   # noqa: E731
            a, b, c, mesh=mesh.jax_mesh, axis="sp", causal=True)
        t_uly = _bench(uly, q, k, v)
        print(f"  ulysses(sp=1 degenerate) {t_uly:.2f} ms "
              f"| ratio {t_uly / t_flash:.3f}")
        return

    # mesh mode: scaling over sp on the virtual CPU mesh
    jax.config.update("jax_platforms", "cpu")
    from paddle_tpu.distributed.mesh import init_mesh
    for n in (1, 2, 4, 8):
        if len(jax.devices()) < n:
            continue
        mesh = init_mesh({"sp": n})
        q = jnp.asarray(rng.randn(1, S, 8, 32), jnp.float32)
        k = jnp.asarray(rng.randn(1, S, 8, 32), jnp.float32)
        v = jnp.asarray(rng.randn(1, S, 8, 32), jnp.float32)
        ring = lambda a, b, c, m=mesh: ring_attention(   # noqa: E731
            a, b, c, mesh=m.jax_mesh, axis="sp", causal=True)
        t = _bench(ring, q, k, v)
        print(f"sp={n}: ring {t:.2f} ms (S={S} local {S // n})")


if __name__ == "__main__":
    main()
