#!/usr/bin/env python
"""Op micro-benchmark runner with regression gating (reference:
tools/ci_op_benchmark.sh + tools/check_op_benchmark_result.py — the
reference CI runs op benchmarks per PR and fails on relative
regressions; this is the paddle_tpu equivalent over the defop registry).

Usage:
  python tools/op_bench.py run  [--out results.json] [--ops add,matmul]
  python tools/op_bench.py check --base base.json --new results.json \
      [--threshold 0.15]

`run` times a curated set of representative ops on the current backend
and writes {op: {shape, ms}} JSON. `check` compares two result files and
exits 1 if any op slowed down by more than the threshold (the reference's
check_op_benchmark_result.py contract).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

# runnable as `python tools/op_bench.py` from the repo root without install
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _cases():
    import numpy as np
    import paddle_tpu as paddle

    rng = np.random.RandomState(0)
    a2 = paddle.to_tensor(rng.randn(1024, 1024).astype(np.float32))
    b2 = paddle.to_tensor(rng.randn(1024, 1024).astype(np.float32))
    v = paddle.to_tensor(rng.randn(4, 512, 1024).astype(np.float32))
    ids = paddle.to_tensor(rng.randint(0, 32000, (8, 512)))
    emb_w = paddle.to_tensor(rng.randn(32000, 256).astype(np.float32))
    q = paddle.to_tensor(rng.randn(2, 512, 8, 64).astype(np.float32))

    from paddle_tpu.nn import functional as F
    return {
        "matmul_1k": ("1024x1024 @ 1024x1024",
                      lambda: paddle.matmul(a2, b2)),
        "add": ("1024x1024 + 1024x1024", lambda: a2 + b2),
        "softmax": ("(4,512,1024) softmax", lambda: F.softmax(v, axis=-1)),
        "layer_norm": ("(4,512,1024) layer_norm",
                       lambda: F.layer_norm(v, [1024])),
        "gelu": ("(4,512,1024) gelu", lambda: F.gelu(v)),
        "embedding": ("(8,512) gather of (32000,256)",
                      lambda: F.embedding(ids, emb_w)),
        "sdpa_causal": ("(2,512,8,64) causal attention",
                        lambda: F.scaled_dot_product_attention(
                            q, q, q, is_causal=True)),
        "reduce_sum": ("(4,512,1024) sum", lambda: v.sum()),
        "transpose": ("(4,512,1024) transpose",
                      lambda: paddle.transpose(v, [0, 2, 1])),
        "cumsum": ("(4,512,1024) cumsum",
                   lambda: paddle.cumsum(v, axis=-1)),
        "flash_fwd": ("(2,2048,8|2,64) bf16 causal GQA flash fwd",
                      _flash_fwd_case(rng)),
        "flash_fwd_bwd": ("(2,2048,8|2,64) bf16 causal GQA flash fwd+bwd",
                          _flash_bwd_case(rng)),
    }


def _flash_qkv(rng):
    import jax.numpy as jnp
    q = jnp.asarray(rng.randn(2, 8, 2048, 64), jnp.bfloat16)
    k = jnp.asarray(rng.randn(2, 2, 2048, 64), jnp.bfloat16)
    v = jnp.asarray(rng.randn(2, 2, 2048, 64), jnp.bfloat16)
    return q, k, v


def _flash_fwd_case(rng):
    import jax
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.kernels.flash_attention import flash_attention_bhsd
    q, k, v = _flash_qkv(rng)
    f = jax.jit(lambda q, k, v: flash_attention_bhsd(q, k, v, causal=True))

    def run():
        # precision context must surround the TRACING call (first run),
        # not jit construction, to reach dots without explicit precision
        with jax.default_matmul_precision("default"):
            return Tensor(f(q, k, v))
    return run


def _flash_bwd_case(rng):
    import jax
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.kernels.flash_attention import flash_attention_bhsd
    q, k, v = _flash_qkv(rng)

    def loss(q, k, v):
        import jax.numpy as jnp
        return flash_attention_bhsd(q, k, v, causal=True).astype(
            jnp.float32).sum()

    g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))

    def run():
        with jax.default_matmul_precision("default"):
            return Tensor(g(q, k, v)[0])
    return run


def _time_one(fn, warmup=2, iters=10):
    import numpy as np
    out = None
    for _ in range(warmup):
        out = fn()
    leaf = out[0] if isinstance(out, (tuple, list)) else out
    np.asarray(leaf._value)  # sync
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn()
    leaf = out[0] if isinstance(out, (tuple, list)) else out
    np.asarray(leaf._value)
    return (time.perf_counter() - t0) / iters * 1000.0


def cmd_run(args):
    import jax
    cases = _cases()
    selected = (set(args.ops.split(",")) if args.ops else set(cases))
    unknown = selected - set(cases)
    if unknown:
        print(f"unknown op(s): {sorted(unknown)}; available: "
              f"{sorted(cases)}")
        return 2
    results = {"device": str(jax.devices()[0]), "ops": {}}
    for name, (desc, fn) in cases.items():
        if name not in selected:
            continue
        ms = _time_one(fn)
        results["ops"][name] = {"shape": desc, "ms": round(ms, 4)}
        print(f"{name:14s} {ms:8.3f} ms   ({desc})")
    with open(args.out, "w") as f:
        json.dump(results, f, indent=1)
    print(f"wrote {args.out}")
    return 0


def cmd_check(args):
    base = json.load(open(args.base))["ops"]
    new = json.load(open(args.new))["ops"]
    common = set(base) & set(new)
    if not common:
        print("FAILED: no ops in common between base and new results — "
              "the gate would be vacuous")
        return 1
    dropped = sorted(set(base) - set(new))
    if dropped:
        print(f"WARNING: ops present in base but missing from new "
              f"(renamed/removed?): {dropped}")
    failures = []
    for name, rec in new.items():
        if name not in base:
            continue
        ratio = rec["ms"] / max(base[name]["ms"], 1e-9)
        status = "OK"
        if ratio > 1 + args.threshold:
            status = "REGRESSION"
            failures.append((name, ratio))
        print(f"{name:14s} base={base[name]['ms']:8.3f} "
              f"new={rec['ms']:8.3f} x{ratio:5.2f}  {status}")
    if failures:
        print(f"FAILED: {len(failures)} op(s) regressed beyond "
              f"{args.threshold:.0%}: "
              + ", ".join(f"{n} (x{r:.2f})" for n, r in failures))
        return 1
    print("all ops within threshold")
    return 0


def main(argv=None):
    p = argparse.ArgumentParser(prog="op_bench")
    sub = p.add_subparsers(dest="cmd", required=True)
    pr = sub.add_parser("run")
    pr.add_argument("--out", default="op_bench_results.json")
    pr.add_argument("--ops", default=None)
    pc = sub.add_parser("check")
    pc.add_argument("--base", required=True)
    pc.add_argument("--new", required=True)
    pc.add_argument("--threshold", type=float, default=0.15)
    args = p.parse_args(argv)
    return cmd_run(args) if args.cmd == "run" else cmd_check(args)


if __name__ == "__main__":
    sys.exit(main())
