"""Secondary model-family benchmarks on the attached TPU chip: the
long-context Llama ladder (S=8k/16k/32k b1, remat, streamed-kv flash
kernels), Qwen2-MoE expert-parallel-shaped train step, and a DiT
forward+backward — the BASELINE.md tracking-table rows beyond the
headline bench.py metric. Run single-process under the default env:
    python tools/model_bench.py [long|moe|dit|all]
Sync discipline per BASELINE.md: fetch the scalar loss, never
block_until_ready.
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np


def _measure_steps(trainer, batch, steps=6, repeats=5):
    """Median-of-`repeats` timed windows of `steps` in-jit steps each
    (VERDICT r3 item 6: a single window on this tunnel-attached rig has
    a multi-x spread; the median over several amortized windows plus a
    reported band is the protocol). Returns (median_dt, loss, spread)
    where spread = (max-min)/median over the windows."""
    import statistics
    import jax.numpy as jnp
    # pre-stage the batch on device ONCE (bench.py protocol): a numpy
    # batch re-crosses the dispatch tunnel every step, which dominates
    # sub-100ms steps (the r3 DiT row's 3.6x spread was exactly this)
    batch = {k: jnp.asarray(v) for k, v in batch.items()}
    float(trainer.step(batch))                 # compile + sync
    times = []
    loss = None
    for _ in range(repeats):
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = trainer.step(batch)
        loss = float(loss)                     # sync closes the chain
        times.append((time.perf_counter() - t0) / steps)
    med = statistics.median(times)
    spread = (max(times) - min(times)) / med if med else 0.0
    return med, loss, spread


def bench_long_context():
    import paddle_tpu as paddle
    import paddle_tpu.optimizer as opt
    from paddle_tpu.models import LlamaForCausalLM, LlamaConfig
    from paddle_tpu.parallel import Trainer, TrainStepConfig

    rng = np.random.RandomState(0)
    for S in (8192, 16384, 32768):
        paddle.seed(0)
        cfg = LlamaConfig(
            vocab_size=32000, hidden_size=1280, intermediate_size=3584,
            num_hidden_layers=16, num_attention_heads=20,
            num_key_value_heads=4, max_position_embeddings=S,
            rope_theta=10000.0, seq_length=S, recompute=True,
            use_flash_attention=True)
        model = LlamaForCausalLM(cfg)
        optimizer = opt.AdamW(learning_rate=1e-4,
                              parameters=model.parameters())
        tr = Trainer(model, optimizer,
                     config=TrainStepConfig(compute_dtype="bfloat16"))
        ids = rng.randint(0, cfg.vocab_size, (1, S)).astype(np.int32)
        dt, loss, sp = _measure_steps(tr, {"input_ids": ids,
                                           "labels": ids})
        print(f"long-context S={S}: {S/dt:,.0f} tok/s/chip "
              f"({dt*1e3:.0f} ms/step, spread {sp:.1%}, "
              f"loss {loss:.3f})", flush=True)
        del tr, model, optimizer


def bench_moe():
    import paddle_tpu as paddle
    import paddle_tpu.optimizer as opt
    from paddle_tpu.models.qwen2_moe import (Qwen2MoeForCausalLM,
                                             tiny_qwen2_moe_config)
    from paddle_tpu.parallel import Trainer, TrainStepConfig

    rng = np.random.RandomState(0)
    paddle.seed(0)
    cfg = tiny_qwen2_moe_config(
        vocab_size=32000, hidden_size=1024, intermediate_size=2816,
        moe_intermediate_size=1408, num_hidden_layers=8,
        num_attention_heads=16, num_key_value_heads=4, num_experts=8,
        num_experts_per_tok=2, seq_length=2048,
        max_position_embeddings=2048, use_flash_attention=True,
        shared_expert_intermediate_size=1408)
    B, S = 4, 2048
    ids = rng.randint(0, cfg.vocab_size, (B, S)).astype(np.int32)
    for variant in ("capacity", "dropless"):
        paddle.seed(0)
        cfg.moe_dropless = variant == "dropless"
        model = Qwen2MoeForCausalLM(cfg)
        optimizer = opt.AdamW(learning_rate=1e-4,
                              parameters=model.parameters())
        tr = Trainer(model, optimizer,
                     config=TrainStepConfig(compute_dtype="bfloat16"))
        dt, loss, sp = _measure_steps(tr, {"input_ids": ids,
                                           "labels": ids})
        print(f"qwen2-moe[{variant}] b{B} s{S}: {B*S/dt:,.0f} "
              f"tok/s/chip ({dt*1e3:.0f} ms/step, spread {sp:.1%}, "
              f"loss {loss:.3f})", flush=True)
        del tr, model, optimizer


def bench_dit():
    import paddle_tpu as paddle
    import paddle_tpu.tensor as T

    rng = np.random.RandomState(0)
    paddle.seed(0)
    from paddle_tpu.models import dit
    # DiT-S/2 on 32x32x4 latents, class-conditional (r1/r2 protocol)
    cfg = dit.DiTConfig(input_size=32, patch_size=2, in_channels=4,
                        hidden_size=384, num_layers=12,
                        num_attention_heads=6, num_classes=1000)
    model = dit.DiT(cfg)
    import paddle_tpu.optimizer as opt
    from paddle_tpu.jit.functional import functional_call
    from paddle_tpu.parallel import Trainer, TrainStepConfig
    optimizer = opt.AdamW(learning_rate=1e-4,
                          parameters=model.parameters())

    def loss_fn(m, params_c, targs):
        x = T.cast(targs["x"], "bfloat16")     # match compute dtype
        out = functional_call(m, params_c, x, targs["t"], targs["y"])
        return T.mean(T.cast(out, "float32") ** 2)

    tr = Trainer(model, optimizer, loss_fn=loss_fn,
                 config=TrainStepConfig(compute_dtype="bfloat16"))
    # b64 = the BASELINE.md figure (b8 is launch-bound, b128 spills)
    B = int(os.environ.get("PT_DIT_BATCH", "64"))
    batch = {"x": rng.randn(B, 4, 32, 32).astype("float32"),
             "t": rng.randint(0, 1000, (B,)).astype(np.int32),
             "y": rng.randint(0, 1000, (B,)).astype(np.int32)}
    dt, loss, sp = _measure_steps(tr, batch, steps=30, repeats=5)
    print(f"dit-s/2 b{B}: {B/dt:,.0f} imgs/s fwd+bwd+Adam "
          f"({dt*1e3:.1f} ms/step, spread {sp:.1%}, loss {loss:.4f})",
          flush=True)


if __name__ == "__main__":
    which = sys.argv[1] if len(sys.argv) > 1 else "all"
    if which in ("long", "all"):
        bench_long_context()
    if which in ("moe", "all"):
        bench_moe()
    if which in ("dit", "all"):
        bench_dit()
