"""Benchmark: Llama pretraining step throughput on the available chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Metric is tokens/sec/chip on a Llama-1B-class pretrain step (fwd+bwd+Adam,
bf16 compute, fp32 master weights, recompute on) — the single-chip proxy
for BASELINE.json's north star (Llama-3-8B >=40% MFU on v5p-64).
vs_baseline = measured MFU / 0.40 (the north-star MFU target; the reference
repo publishes no absolute numbers — BASELINE.md).
"""
from __future__ import annotations

import json
import os
import time

import numpy as np


# bf16 peak FLOP/s per chip by device kind (public TPU specs)
_PEAK = {
    "TPU v4": 275e12,
    "TPU v5": 459e12,        # v5p
    "TPU v5 lite": 197e12,   # v5e
    "TPU v5e": 197e12,
    "TPU v6 lite": 918e12,   # v6e / Trillium
    "TPU v6e": 918e12,
}


def _peak_flops(dev) -> float:
    kind = getattr(dev, "device_kind", "") or ""
    # longest key first: "TPU v5 lite" must match before "TPU v5" —
    # rounds 1..2 matched in dict order and scored the v5e against the
    # v5p peak (459 vs 197 TF/s), understating MFU ~2.3x
    for k in sorted(_PEAK, key=len, reverse=True):
        if kind.startswith(k) or k in kind:
            return _PEAK[k]
    return 459e12  # assume v5p (the north-star part)


def _decode_bench(on_tpu):
    """Serving decode microbench: aggregate tok/s and KV bytes/slot at
    a fixed slot count, for the jnp attend path, the Pallas
    paged-decode kernel (interpret mode off-TPU — a parity/coverage
    config there, a perf config on real chips), and the kernel with
    int8 KV pools. The measured run executes under a scoped
    observability enable, so the request-tracing layer
    (observability/requests.py) records per-request TTFT and
    inter-token latency; their p50/p95/p99 ride each row (the
    user-felt serving SLOs next to the aggregate throughput).
    Returns a list of row dicts for the BENCH json."""
    import time

    import paddle_tpu
    from paddle_tpu import observability
    from paddle_tpu.inference.paged import PagedKVEngine
    from paddle_tpu.models.llama import LlamaForCausalLM, LlamaConfig, \
        tiny_llama_config

    paddle_tpu.seed(0)
    if on_tpu:
        cfg = LlamaConfig(
            vocab_size=32000, hidden_size=1024, intermediate_size=2816,
            num_hidden_layers=8, num_attention_heads=16,
            num_key_value_heads=4, max_position_embeddings=1024,
            rope_theta=10000.0, seq_length=1024)
        # page_size 32: the int8 row's (page_size, d) k/v block must
        # tile the int8 Mosaic sublane minimum of 32 when compiled
        slots, page_size, num_pages, max_new = 8, 32, 256, 64
    else:
        cfg = tiny_llama_config(num_hidden_layers=2, vocab_size=128,
                                hidden_size=64, intermediate_size=128,
                                num_attention_heads=4,
                                num_key_value_heads=2)
        slots, page_size, num_pages, max_new = 4, 8, 64, 16
    model = LlamaForCausalLM(cfg)
    rng = np.random.RandomState(0)
    prompts = [list(rng.randint(1, cfg.vocab_size, 12))
               for _ in range(slots)]

    rows = []
    for label, kernel, kv_dtype in (
            ("jnp", "jnp", "bf16"),
            ("pallas", "pallas", "bf16"),
            ("pallas+int8", "pallas", "int8")):
        eng = PagedKVEngine(
            model, max_slots=slots, page_size=page_size,
            num_pages=num_pages, steps_per_tick=4, kernel=kernel,
            kv_dtype=kv_dtype)
        eng.generate(prompts, max_new_tokens=2)      # compile warmup
        base_tokens = eng.stats["tokens_out"]
        with observability.scoped(reset=True) as reg:
            t0 = time.perf_counter()
            eng.generate(prompts, max_new_tokens=max_new)
            dt = time.perf_counter() - t0

        def _pcts(name):
            h = reg.histogram(name)
            if h.count() == 0:
                return None
            return {f"p{p}": round(h.percentile(p) * 1000.0, 3)
                    for p in (50, 95, 99)}

        rows.append({
            "path": label,
            "tokens_per_sec": round(
                (eng.stats["tokens_out"] - base_tokens) / dt, 2),
            "kv_bytes_per_slot": eng.kv_bytes_per_slot(),
            "slots": slots,
            "ttft_ms": _pcts("request.ttft.seconds"),
            "itl_ms": _pcts("request.itl.seconds"),
        })
    return rows


def _prefix_bench():
    """Prefix-cache payoff (ISSUE 11): a shared-system-prompt serving
    workload — K requests carrying one common multi-page prefix with
    distinct tails — run twice through the SAME engine: cold (the
    `prefix.cache.bypass` chaos site forces every lookup to miss, so
    every request prefills its whole prompt) and warm (cache on: each
    request prefills only its uncached tail). Reports prompt tokens
    admitted per second of prefill wall time for both passes, the
    warm-pass hit rate, and pages shared — the claim is warm >= 2x
    cold, because prefill work drops from O(prompt) to O(tail).
    Compiles are excluded by running both modes once before timing."""
    import time

    import paddle_tpu
    from paddle_tpu.distributed import chaos
    from paddle_tpu.inference.paged import PagedKVEngine
    from paddle_tpu.models.llama import LlamaForCausalLM, \
        tiny_llama_config

    paddle_tpu.seed(0)
    cfg = tiny_llama_config(num_hidden_layers=2, vocab_size=128,
                            hidden_size=64, intermediate_size=128,
                            num_attention_heads=4,
                            num_key_value_heads=2)
    model = LlamaForCausalLM(cfg)
    page_size, prefix_pages, k_req = 16, 2, 6
    rng = np.random.RandomState(0)
    prefix = list(rng.randint(1, cfg.vocab_size,
                              prefix_pages * page_size))
    prompts = [prefix + list(rng.randint(1, cfg.vocab_size, 8))
               for _ in range(k_req)]
    eng = PagedKVEngine(model, max_slots=4, page_size=page_size,
                        num_pages=128, steps_per_tick=2,
                        prefix_cache_pages=32)
    tokens = sum(len(p) for p in prompts)

    def run_pass(bypass):
        s0 = dict(eng.stats)
        if bypass:
            with chaos.scoped(rates={"prefix.cache.bypass": 1.0}):
                t0 = time.perf_counter()
                eng.generate(prompts, max_new_tokens=2)
                dt = time.perf_counter() - t0
        else:
            t0 = time.perf_counter()
            eng.generate(prompts, max_new_tokens=2)
            dt = time.perf_counter() - t0
        return dt, {k: eng.stats[k] - s0[k]
                    for k in ("prefill_s", "prefix_hits",
                              "prefix_misses", "prefix_pages_shared")}

    run_pass(True)      # warmup: compiles the full-prompt bucket,
    run_pass(False)     # seeds the cache + compiles the tail bucket
    _dt, cold = run_pass(True)
    _dt, warm = run_pass(False)
    cold_tps = tokens / max(cold["prefill_s"], 1e-9)
    warm_tps = tokens / max(warm["prefill_s"], 1e-9)
    denom = warm["prefix_hits"] + warm["prefix_misses"]
    return {
        "requests": k_req,
        "page_size": page_size,
        "prefix_tokens": prefix_pages * page_size,
        "prompt_tokens": tokens,
        "cold_prefill_tokens_per_sec": round(cold_tps, 2),
        "warm_prefill_tokens_per_sec": round(warm_tps, 2),
        "warm_vs_cold": round(warm_tps / max(cold_tps, 1e-9), 3),
        "hit_rate": round(warm["prefix_hits"] / denom, 4) if denom
        else 0.0,
        "pages_shared": warm["prefix_pages_shared"],
        "cached_pages": len(eng.prefix_cache),
    }


def _kvtier_bench():
    """Tiered-KV payoff (ISSUE 18), two numbers the acceptance gate
    names: (1) restore-hit prefill tokens/sec vs cold — K requests
    sharing a multi-page prefix whose pages were EVICTED to the host
    tier run against K never-seen prompts of identical shape (same
    compile buckets, so only the prefill work differs: a restore is
    O(tail) + one H2D batch, cold is O(prompt)); (2) the
    suspend/resume round trip — one session's turn, an idle window
    that spills its pages and frees HBM, then the next turn restored
    from host. Compiles are excluded by a warmup pass of both
    buckets."""
    import time

    import paddle_tpu
    from paddle_tpu.inference.paged import PagedKVEngine
    from paddle_tpu.models.llama import LlamaForCausalLM, \
        tiny_llama_config

    paddle_tpu.seed(0)
    cfg = tiny_llama_config(num_hidden_layers=2, vocab_size=128,
                            hidden_size=64, intermediate_size=128,
                            num_attention_heads=4,
                            num_key_value_heads=2)
    model = LlamaForCausalLM(cfg)
    page_size, prefix_pages, k_req = 16, 4, 4
    rng = np.random.RandomState(0)
    prefix = list(rng.randint(1, cfg.vocab_size,
                              prefix_pages * page_size))
    tails = [list(rng.randint(1, cfg.vocab_size, 8))
             for _ in range(k_req)]

    def fresh(n):
        return [list(rng.randint(1, cfg.vocab_size, len(prefix) + 8))
                for _ in range(n)]

    def fresh_tails(n):
        return [list(rng.randint(1, cfg.vocab_size, 8))
                for _ in range(n)]

    eng = PagedKVEngine(model, max_slots=4, page_size=page_size,
                        num_pages=128, steps_per_tick=2,
                        prefix_cache_pages=prefix_pages + 2,
                        host_tier_bytes=64 << 20)
    tokens = k_req * (len(prefix) + 8)

    from paddle_tpu.inference.prefix import chain_keys
    prefix_keys = chain_keys(prefix, page_size)

    def run_pass(prompts):
        s0 = eng.stats["prefill_s"]
        eng.generate(prompts, max_new_tokens=2)
        return eng.stats["prefill_s"] - s0

    def evict_device_cache():
        # distinct same-shape prompts churn the small device cache
        # until the prefix keys are gone (each eviction spills)
        while any(k in eng.prefix_cache for k in prefix_keys):
            run_pass(fresh(2))
        eng.host_tier.flush()

    # warmup compiles every (bucket, batch-width) the measured passes
    # use: full-prompt bucket at width k (cold pass), then — with the
    # prefix cached by the first group — the tail bucket at width k
    # (restore pass runs the same warm prefill)
    run_pass([prefix + t for t in tails])
    run_pass([prefix + t for t in fresh_tails(k_req)])
    evict_device_cache()

    cold_s = run_pass(fresh(k_req))
    evict_device_cache()
    pre = eng.host_tier.snapshot()
    restore_s = run_pass([prefix + t for t in tails])
    snap = eng.host_tier.snapshot()
    dlk = snap["lookups"] - pre["lookups"]
    pass_hit_rate = (round((snap["hits"] - pre["hits"]) / dlk, 4)
                     if dlk else 0.0)
    cold_tps = tokens / max(cold_s, 1e-9)
    restore_tps = tokens / max(restore_s, 1e-9)
    eng.stop()

    # suspend/resume round trip on a fresh session engine
    eng2 = PagedKVEngine(model, max_slots=4, page_size=page_size,
                         num_pages=128, steps_per_tick=2,
                         prefix_cache_pages=32,
                         host_tier_bytes=64 << 20,
                         suspend_after_s=0.01)
    def turn_pair(session):
        t1 = list(rng.randint(1, cfg.vocab_size, 40))
        r = eng2.submit(np.asarray(t1, np.int32), max_new_tokens=8,
                        session=session)
        eng2.run_until_idle()
        return t1, r.result()

    # warmup pair: compiles the turn-1 bucket and the warm turn-2 tail
    # bucket so the measured round trip times transfers, not XLA
    w1, wout = turn_pair("warmup")
    w2 = w1 + wout + list(rng.randint(1, cfg.vocab_size, 8))
    eng2.submit(np.asarray(w2, np.int32), max_new_tokens=2,
                session="warmup")
    eng2.run_until_idle()

    turn1, out1 = turn_pair("bench")
    time.sleep(0.02)
    t0 = time.perf_counter()
    eng2.step()                     # sweep spills the idle session
    eng2.host_tier.flush()
    suspend_ms = (time.perf_counter() - t0) * 1e3
    turn2 = turn1 + out1 + list(rng.randint(1, cfg.vocab_size, 8))
    t0 = time.perf_counter()
    r2 = eng2.submit(np.asarray(turn2, np.int32), max_new_tokens=2,
                     session="bench")
    eng2.run_until_idle()
    r2.result()
    resume_ms = (time.perf_counter() - t0) * 1e3
    snap2 = eng2.host_tier.snapshot()
    eng2.stop()

    return {
        "requests": k_req,
        "prefix_tokens": prefix_pages * page_size,
        "prompt_tokens": tokens,
        "cold_prefill_tokens_per_sec": round(cold_tps, 2),
        "restore_prefill_tokens_per_sec": round(restore_tps, 2),
        "restore_vs_cold": round(restore_tps / max(cold_tps, 1e-9), 3),
        "tier_hit_rate": pass_hit_rate,
        "tier_hit_rate_lifetime": snap["hit_rate"],
        "restored_pages": snap["restored_pages"],
        "spilled_pages": snap["spilled_pages"],
        "spill_bytes": snap["spill_bytes"],
        "suspend_ms": round(suspend_ms, 2),
        "resume_roundtrip_ms": round(resume_ms, 2),
        "suspends": snap2["suspends"],
        "resumes": snap2["resumes"],
    }


def _disagg_bench():
    """Disaggregated prefill/decode payoff (ISSUE 20): the SAME
    shared-prefix workload run monolithic (one engine does both
    phases) and pooled (a role="prefill" engine prefills + exports,
    a role="decode" engine imports + decodes, page bundles moving
    through the pack/unpack wire format). Three claims, reported as
    numbers: (1) pooled output is EXACTLY the monolithic tokens
    (handoff is lossless); (2) chain-key dedup + int8 pools cut the
    bytes moved >= 2x vs a naive bf16 full-page transfer (shared
    prefix pages move once, not once per request; int8+scales is
    ~0.52x bf16); (3) the per-request handoff cost in ms (the TTFT
    tax the decode pool pays for never running prefill). Compiles
    excluded by a warmup pass through both engines."""
    import time

    import paddle_tpu
    from paddle_tpu.inference.disagg import pack_bundle, unpack_bundle
    from paddle_tpu.inference.paged import PagedKVEngine
    from paddle_tpu.inference.prefix import chain_keys
    from paddle_tpu.models.llama import LlamaForCausalLM, \
        tiny_llama_config

    paddle_tpu.seed(0)
    cfg = tiny_llama_config(num_hidden_layers=2, vocab_size=128,
                            hidden_size=64, intermediate_size=128,
                            num_attention_heads=4,
                            num_key_value_heads=2)
    model = LlamaForCausalLM(cfg)
    page_size, k_req, new_toks = 16, 4, 8
    rng = np.random.RandomState(0)
    prefix = list(rng.randint(1, cfg.vocab_size, 2 * page_size))
    # each request: 2 shared prefix pages + 1 unique full page + tail
    prompts = [prefix + list(rng.randint(1, cfg.vocab_size,
                                         page_size + 4))
               for _ in range(k_req)]
    kw = dict(max_slots=4, page_size=page_size, num_pages=64,
              steps_per_tick=2, prefix_cache_pages=16,
              kv_dtype="int8")

    # warmup prompt: same shape as the workload, sharing the prefix
    # but not any measured unique page — compiles the full-prompt AND
    # warm-tail buckets in every engine before timing starts
    warm = prefix + list(rng.randint(1, cfg.vocab_size, page_size + 4))

    mono = PagedKVEngine(model, **kw)
    mono.generate([prompts[0]], max_new_tokens=2)        # full bucket
    mono.generate([warm], max_new_tokens=2)              # tail bucket
    t0 = time.perf_counter()
    want = mono.generate(prompts, max_new_tokens=new_toks)
    mono_s = time.perf_counter() - t0
    mono.stop()

    pre = PagedKVEngine(model, role="prefill",
                        host_tier_bytes=64 << 20, **kw)
    dec = PagedKVEngine(model, role="decode", **kw)
    pre.generate([prompts[0]], max_new_tokens=1)         # warmup
    pre.generate([warm], max_new_tokens=1)
    dec.generate([prompts[0]], max_new_tokens=2)
    dec.generate([warm], max_new_tokens=2)
    # naive baseline: every full page of every request ships as bf16
    # k+v (2 bytes/elem), no dedup — what a handoff without chain
    # keys or quantization would move
    elems_per_page = (cfg.num_hidden_layers * 2 * page_size
                      * cfg.num_key_value_heads
                      * (cfg.hidden_size // cfg.num_attention_heads))
    pages_total = sum(len(p) // page_size for p in prompts)
    naive_bytes = pages_total * elems_per_page * 2
    moved_bytes = moved_pages = dedup_pages = 0
    handoff_ms = []
    got = []
    t0 = time.perf_counter()
    for p in prompts:
        pre.generate([p], max_new_tokens=1)              # hop 1
        keys = chain_keys(p, page_size)
        h0 = time.perf_counter()
        missing = dec.disagg_missing(keys)
        dedup_pages += len(keys) - len(missing)
        ents = [e for e in pre.export_pages(keys)
                if e.key in set(missing)]
        raw = pack_bundle(ents)
        dec.stage_import(unpack_bundle(raw))
        handoff_ms.append((time.perf_counter() - h0) * 1e3)
        moved_bytes += len(raw)
        moved_pages += len(ents)
        got.append(dec.generate([p],                     # hop 2
                                max_new_tokens=new_toks)[0])
    pooled_s = time.perf_counter() - t0
    parity = got == want
    pre.stop()
    dec.stop()

    toks = k_req * new_toks
    return {
        "requests": k_req,
        "prompt_pages": pages_total,
        "parity": parity,
        "mono_tokens_per_sec": round(toks / max(mono_s, 1e-9), 2),
        "pooled_tokens_per_sec": round(toks / max(pooled_s, 1e-9), 2),
        "handoff_ms_mean": round(sum(handoff_ms) / len(handoff_ms), 3),
        "moved_pages": moved_pages,
        "moved_bytes": moved_bytes,
        "naive_bf16_bytes": naive_bytes,
        "bytes_reduction": round(naive_bytes / max(moved_bytes, 1), 3),
        "dedup_skipped_pages": dedup_pages,
    }


def _tenant_bench():
    """Multi-tenant QoS payoff (ISSUE 13): a saturated two-tenant
    workload — `prod` (weight 3) and `batch` (weight 1) each submit
    more requests than the engine has slots — through ONE engine with
    a TenantTable. Reports the decode slot-tick split (the claim:
    ~3:1 by weight, from the engine's own per-tenant counters), the
    admission interleave, and the per-tenant queue-wait means: the
    weighted-fair pick turns the old FIFO pot-luck into a policy
    number. Pure host-side scheduling on the same tiny model the
    prefix bench uses; compiles excluded by a warmup pass."""
    import time

    import paddle_tpu
    from paddle_tpu.inference.paged import PagedKVEngine
    from paddle_tpu.inference.tenancy import TenantPolicy, TenantTable
    from paddle_tpu.models.llama import LlamaForCausalLM, \
        tiny_llama_config

    paddle_tpu.seed(0)
    cfg = tiny_llama_config(num_hidden_layers=2, vocab_size=128,
                            hidden_size=64, intermediate_size=128,
                            num_attention_heads=4,
                            num_key_value_heads=2)
    model = LlamaForCausalLM(cfg)
    table = TenantTable([TenantPolicy("prod", weight=3.0),
                         TenantPolicy("batch", weight=1.0)])
    eng = PagedKVEngine(model, max_slots=2, page_size=16,
                        num_pages=128, steps_per_tick=2,
                        tenancy=table)
    rng = np.random.RandomState(0)

    def submit_all(n_per_tenant, max_new):
        reqs = []
        for _ in range(n_per_tenant):
            for t in ("prod", "batch"):
                reqs.append(eng.submit(
                    list(rng.randint(1, cfg.vocab_size, 8)),
                    max_new_tokens=max_new, tenant=t))
        return reqs

    warm = submit_all(1, 2)         # warmup: compiles
    eng.run_until_idle()
    for r in warm:
        r.result()
    base = {k: dict(v) for k, v in eng.tenant_snapshot().items()}
    reqs = submit_all(8, 8)
    # the weighted split only exists while BOTH tenants are
    # backlogged (a drained workload equalizes lifetime totals):
    # snapshot slot shares the moment one side's backlog empties
    t0 = time.perf_counter()
    saturated = None
    while eng.has_work():
        eng.step()
        snap = eng.tenant_snapshot()
        if saturated is None and (snap["prod"]["pending"] == 0
                                  or snap["batch"]["pending"] == 0):
            saturated = {
                t: snap[t]["slot_ticks"]
                - base.get(t, {}).get("slot_ticks", 0)
                for t in ("prod", "batch")}
    dt = time.perf_counter() - t0
    for r in reqs:
        r.result()
    snap = eng.tenant_snapshot()

    def delta(t, k):
        return snap[t][k] - base.get(t, {}).get(k, 0)

    sat = saturated or {"prod": 0, "batch": 0}
    return {
        "requests_per_tenant": 8,
        "weights": {"prod": 3.0, "batch": 1.0},
        "wall_s": round(dt, 3),
        "saturated_slot_ticks": sat,
        "saturated_share_ratio": round(
            sat["prod"] / max(sat["batch"], 1), 3),
        "admitted": {"prod": delta("prod", "admitted"),
                     "batch": delta("batch", "admitted")},
    }


def _train_breakdown(on_tpu):
    """Fused-vs-dense loss-path A/B (ISSUE 14) on the SAME model
    config: two fresh same-seed models — one with the blockwise CE
    (`loss_chunk`) + fused norm/rope train path, one on the dense
    logits path (`loss_chunk=0`) — each driven through a Trainer for a
    few timed steps. Reports tokens/sec and the peak logits-path bytes
    per path (dense materializes [B*S, V]; blockwise peaks at
    O(chunk x V)), the loss delta (the parity evidence), and the
    phase-attributed step seconds from `Trainer.measure_phase_seconds`
    read back out of the new `train.phase.seconds` instruments — so
    the bench JSON says WHY the train metric moved."""
    import time

    import paddle_tpu
    import paddle_tpu.optimizer as opt
    from paddle_tpu import observability
    from paddle_tpu.kernels.blockwise_ce import dense_logits_bytes, \
        logits_bytes_saved
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM, \
        tiny_llama_config
    from paddle_tpu.parallel import Trainer, TrainStepConfig

    if on_tpu:
        base = dict(vocab_size=32000, hidden_size=1024,
                    intermediate_size=2816, num_hidden_layers=4,
                    num_attention_heads=16, num_key_value_heads=4,
                    max_position_embeddings=1024, rope_theta=10000.0,
                    seq_length=1024)
        make_cfg = lambda **kw: LlamaConfig(**base, **kw)  # noqa: E731
        batch_b, seq, steps, chunk = 4, 1024, 6, 512
        compute_dtype = "bfloat16"
    else:
        make_cfg = lambda **kw: tiny_llama_config(  # noqa: E731
            vocab_size=512, num_hidden_layers=2, hidden_size=64,
            intermediate_size=128, num_attention_heads=4,
            num_key_value_heads=2, **kw)
        batch_b, seq, steps, chunk = 4, 32, 4, 16
        compute_dtype = None

    rng = np.random.RandomState(0)
    ids = rng.randint(0, int(make_cfg().vocab_size),
                      (batch_b, seq)).astype(np.int32)
    item = 2 if compute_dtype == "bfloat16" else 4
    rows_out = []
    for label, overrides in (
            ("dense", {}),
            ("fused", dict(loss_chunk=chunk, fused_norm=True,
                           fused_rope=True))):
        paddle_tpu.seed(0)
        cfg = make_cfg(**overrides)
        model = LlamaForCausalLM(cfg)
        optimizer = opt.AdamW(learning_rate=1e-4,
                              parameters=model.parameters(),
                              weight_decay=0.01)
        trainer = Trainer(model, optimizer, config=TrainStepConfig(
            compute_dtype=compute_dtype))
        batch = {"input_ids": ids, "labels": ids}
        # first-step loss is pre-update on identical seeds: THE parity
        # number (later steps drift as rounding feeds AdamW)
        loss_step1 = float(trainer.step(batch))   # warm + compile
        t0 = time.perf_counter()
        for _ in range(steps):
            loss_t = trainer.step(batch)
        loss = float(loss_t)
        dt = time.perf_counter() - t0
        with observability.scoped(reset=True) as reg:
            trainer.measure_phase_seconds(batch, iters=2)
            h = reg.histogram("train.phase.seconds")
            phases = {}
            for ph in ("fwd", "bwd", "optimizer"):
                cell = h.labeled().get((("phase", ph),))
                phases[ph] = round(cell.sum / max(cell.count, 1), 6) \
                    if cell else None
        n_rows = batch_b * seq
        dense_bytes = dense_logits_bytes(n_rows, cfg.vocab_size, item)
        peak = dense_bytes if not cfg.loss_chunk else \
            dense_bytes - logits_bytes_saved(
                n_rows, cfg.vocab_size, cfg.loss_chunk,
                cfg.loss_vocab_block, item)
        rows_out.append({
            "path": label,
            "loss_chunk": cfg.loss_chunk,
            "tokens_per_sec": round(batch_b * seq * steps / dt, 2),
            "loss_step1": round(loss_step1, 6),
            "loss": round(loss, 6),
            "peak_logits_bytes": int(peak),
            "phase_seconds": phases,
        })
    d, f = rows_out
    return {
        "batch": batch_b, "seq": seq, "steps": steps,
        "vocab_size": int(make_cfg().vocab_size),
        "rows": rows_out,
        "fused_vs_dense_tokens_per_sec": round(
            f["tokens_per_sec"] / max(d["tokens_per_sec"], 1e-9), 4),
        "loss_step1_delta": round(abs(f["loss_step1"]
                                      - d["loss_step1"]), 8),
        "logits_bytes_saved": int(d["peak_logits_bytes"]
                                  - f["peak_logits_bytes"]),
    }


def _overlap_ab():
    """Decomposed-FSDP-collective A/B (ISSUE 19) on a dp x fsdp mesh:
    two fresh same-seed models through the SAME Trainer config/batch —
    one on XLA-propagated collectives, one with the chunked ppermute
    rings (`overlap_fsdp`) — reporting tokens/s, MFU, the first-step
    loss delta (parity evidence) and the overlap fraction + per-phase
    comm seconds from `measure_phase_seconds`'s comm-attribution
    twins. Requires >= 2 jax devices; `_overlap_bench` re-execs with
    forced host devices on a single-device CPU rig."""
    import time

    import jax
    import paddle_tpu
    import paddle_tpu.optimizer as opt
    from paddle_tpu import observability
    from paddle_tpu.distributed import init_mesh
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM, \
        tiny_llama_config
    from paddle_tpu.parallel import Trainer, TrainStepConfig, \
        llama_sharding_plan

    devs = jax.devices()
    n = len(devs)
    if n < 2:
        raise RuntimeError(f"overlap A/B needs >= 2 devices (got {n})")
    on_tpu = devs[0].platform == "tpu"
    fsdp = 4 if n % 4 == 0 else 2
    dp = max(1, n // fsdp)
    mesh = init_mesh({"dp": dp, "fsdp": fsdp})
    if on_tpu:
        make_cfg = lambda: LlamaConfig(  # noqa: E731
            vocab_size=32000, hidden_size=1024, intermediate_size=2816,
            num_hidden_layers=4, num_attention_heads=16,
            num_key_value_heads=4, max_position_embeddings=1024,
            rope_theta=10000.0, seq_length=1024)
        batch_b, seq, steps, chunks = 4 * dp * fsdp, 1024, 6, 4
        compute_dtype = "bfloat16"
    else:
        make_cfg = lambda: tiny_llama_config(  # noqa: E731
            vocab_size=512, num_hidden_layers=2, hidden_size=256,
            intermediate_size=512, num_attention_heads=4,
            num_key_value_heads=2, seq_length=64)
        batch_b, seq, steps, chunks = dp * fsdp, 64, 8, 2
        compute_dtype = None

    rng = np.random.RandomState(0)
    ids = rng.randint(0, int(make_cfg().vocab_size),
                      (batch_b, seq)).astype(np.int32)
    rows_out = []
    frac = comm = None
    for label, overlap in (("propagated", False), ("overlapped", True)):
        paddle_tpu.seed(0)
        cfg = make_cfg()
        model = LlamaForCausalLM(cfg)
        optimizer = opt.AdamW(learning_rate=1e-4,
                              parameters=model.parameters(),
                              weight_decay=0.01)
        trainer = Trainer(
            model, optimizer, mesh=mesh,
            plan=llama_sharding_plan(mesh.jax_mesh.axis_names),
            config=TrainStepConfig(compute_dtype=compute_dtype,
                                   overlap_fsdp=overlap,
                                   overlap_chunks=chunks))
        batch = {"input_ids": ids, "labels": ids}
        loss_step1 = float(trainer.step(batch))   # warm + compile
        t0 = time.perf_counter()
        for _ in range(steps):
            loss_t = trainer.step(batch)
        loss = float(loss_t)
        dt = time.perf_counter() - t0
        toks = batch_b * seq * steps / dt
        n_params = sum(int(np.prod(v.shape))
                       for v in trainer.params.values())
        mfu = (6.0 * n_params * toks / (_peak_flops(devs[0]) * n)
               if on_tpu else 0.0)
        row = {"path": label,
               "tokens_per_sec": round(toks, 2),
               "mfu": round(mfu, 4),
               "loss_step1": round(loss_step1, 6),
               "loss": round(loss, 6)}
        if overlap:
            with observability.scoped(reset=True) as reg:
                phases = trainer.measure_phase_seconds(batch, iters=2)
            frac = phases.get("overlap_fraction")
            comm = {"fwd": round(phases.get("fwd_comm", 0.0), 6),
                    "bwd": round(phases.get("bwd_comm", 0.0), 6)}
            row["overlap_fraction"] = (round(frac, 4)
                                       if frac is not None else None)
            row["comm_seconds"] = comm
        rows_out.append(row)
    p, o = rows_out
    return {
        "mesh": {"dp": dp, "fsdp": fsdp},
        "batch": batch_b, "seq": seq, "steps": steps, "chunks": chunks,
        "rows": rows_out,
        "overlapped_vs_propagated_tokens_per_sec": round(
            o["tokens_per_sec"] / max(p["tokens_per_sec"], 1e-9), 4),
        "overlap_fraction": (round(frac, 4)
                             if frac is not None else None),
        "loss_step1_delta": round(abs(o["loss_step1"]
                                      - p["loss_step1"]), 8),
    }


def _overlap_bench(on_tpu):
    """`extra.overlap` entry: run `_overlap_ab` inline when this
    process already sees >= 2 devices (TPU, or a forced-device CPU
    run); on the default single-device CPU rig, re-exec bench.py with
    8 forced host devices (the backend's device count is frozen at
    first use, so the A/B mesh needs a fresh process) and parse its
    one JSON line."""
    import jax
    if len(jax.devices()) >= 2:
        return _overlap_ab()
    if on_tpu:
        raise RuntimeError("single-device TPU: no fsdp axis to A/B")
    import subprocess
    import sys
    env = dict(os.environ)
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8")
    env["JAX_PLATFORMS"] = "cpu"
    out = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--overlap-ab"],
        capture_output=True, text=True, timeout=900, env=env)
    if out.returncode != 0:
        raise RuntimeError("overlap A/B subprocess failed: "
                           + out.stderr.strip()[-300:])
    return json.loads(out.stdout.strip().splitlines()[-1])


def _fleet_bench(trainer, batch, steps):
    """Heartbeat-publisher overhead (ISSUE 9): the SAME compiled step
    run with observability on, first without the fleet plane, then
    with a FleetHeartbeat publishing into a local TCPStore at an
    aggressively short interval. Reports both tokens/sec numbers and
    the delta — the acceptance claim is that the train metric is
    unchanged with the plane enabled. Also scans the aggregator once
    so the row carries the straggler view a healthy single-rank fleet
    produces (none)."""
    import time

    from paddle_tpu import observability
    from paddle_tpu.distributed.store import TCPStore
    from paddle_tpu.observability.fleet import FleetAggregator

    tokens = 1
    for v in batch.values():
        tokens = int(np.asarray(v).shape[0]) * int(np.asarray(v).shape[1])
        break

    def _run(n):
        t0 = time.perf_counter()
        loss = None
        for _ in range(n):
            loss = trainer.step(batch)
        float(loss)                     # close the dispatch chain
        return time.perf_counter() - t0

    interval = 0.05         # 20 Hz — 40x production cadence (2 s), so
    #                         the measured delta bounds the real cost
    with observability.scoped(reset=True):
        _run(1)                         # warm (telemetry path traced)
        base_dt = _run(steps)
        store = TCPStore(is_master=True, world_size=1)
        try:
            hb = trainer.fleet_heartbeat(store, 0, 1, interval=interval)
            try:
                plane_dt = _run(steps)
            finally:
                hb.stop()
            view = FleetAggregator(store, 1, stale_after_s=60.0).scan()
        finally:
            store.close()
    off = tokens * steps / base_dt
    on = tokens * steps / plane_dt
    return {
        "steps": steps,
        "interval_s": interval,
        "tokens_per_sec_plane_off": round(off, 2),
        "tokens_per_sec_plane_on": round(on, 2),
        "overhead_pct": round((plane_dt - base_dt) / base_dt * 100.0, 2),
        "beats": hb.beats,
        "stragglers": view["summary"]["stragglers"],
    }


def _sentry_bench(on_tpu):
    """Training-sentry cost/benefit (ISSUE 17). (a) Sentry overhead on
    the SAME compiled step (`TrainStepConfig(health_probe=True)`
    built once): a plain step loop vs the loop with the sentry's
    host plane per step — probe readback, EWMA fold, loss-cap staging
    — the acceptance claim is <1% (`overhead_pct`). Primary number:
    the added host segments timed directly inside the on-arm loop
    (`host_us_per_step` over the undisturbed step time), which
    excludes machine noise on the big step in the middle. The
    end-to-end interleaved A/B rides along as `ab_delta_pct` with an
    off-vs-off `aa_floor_pct` control — the delta this machine
    reports when there is NO difference, the error bar on the A/B.
    The compile-level cost of the probe itself (plain config vs
    health_probe config, a second compiled program with the grad-norm
    reduction and param-tree update gate) is `probe_compile_delta_pct`.
    (b) Time-to-recover: a rollback-policy sentried run with one
    injected NaN step (chaos `train.grad.nan`), reporting the
    checkpoint-restore seconds and the whole run's wall time — what
    one numerical fault actually costs end to end."""
    import shutil
    import tempfile
    import time

    import paddle_tpu
    import paddle_tpu.optimizer as opt
    from paddle_tpu.distributed import chaos
    from paddle_tpu.distributed.sentry import SentryConfig, TrainingSentry
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.parallel import Trainer, TrainStepConfig

    if on_tpu:
        cfg = LlamaConfig(vocab_size=32000, hidden_size=1024,
                          intermediate_size=2816, num_hidden_layers=4,
                          num_attention_heads=16, num_key_value_heads=4,
                          max_position_embeddings=1024,
                          rope_theta=10000.0, seq_length=1024)
        batch_b, seq, steps, compute_dtype = 4, 1024, 8, "bfloat16"
    else:
        # NOT tiny_llama_config: the cost under test is a fixed ~40us
        # of host work per step, so the step must be big enough
        # (~120ms here) that sub-1% deltas resolve above this
        # machine's scheduler noise — on a 7ms tiny step the A/A
        # floor alone exceeds 1%
        cfg = LlamaConfig(vocab_size=1024, hidden_size=256,
                          intermediate_size=704, num_hidden_layers=2,
                          num_attention_heads=4, num_key_value_heads=4,
                          max_position_embeddings=128,
                          rope_theta=10000.0, seq_length=128)
        batch_b, seq, steps, compute_dtype = 4, 128, 8, None

    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (batch_b, seq)).astype(np.int32)
    batch = {"input_ids": ids, "labels": ids}

    def make(probe):
        paddle_tpu.seed(0)
        m = LlamaForCausalLM(cfg)
        o = opt.AdamW(learning_rate=1e-4, parameters=m.parameters())
        return Trainer(m, o, config=TrainStepConfig(
            compute_dtype=compute_dtype, health_probe=probe))

    def timed(t, n):
        float(t.step(batch))            # warm + compile
        t0 = time.perf_counter()
        loss = None
        for _ in range(n):
            loss = t.step(batch)
        float(loss)                     # close the dispatch chain
        return time.perf_counter() - t0

    # interleave the A/B arms in short blocks so machine drift lands
    # on both equally; per-arm totals stay small because the big step
    # (not sample count) is what buys resolution here
    ab_block = 4 if on_tpu else 6
    ab_rounds = 2 if on_tpu else 4
    ab_steps = ab_block * ab_rounds
    plain_dt = timed(make(False), ab_steps)
    probed = make(True)
    float(probed.step(batch))           # warm + compile

    def run_off(n):
        # reads the loss per step like any loop that logs it — the
        # sentry's contract is no sync BEYOND that read
        ts = []
        for _ in range(n):
            t0 = time.perf_counter()
            float(probed.step(batch))
            ts.append(time.perf_counter() - t0)
        return ts

    # ONE long-lived sentry across every on-arm rep: a fresh detector
    # re-warms its EWMA and restages the loss cap while it settles,
    # which is a startup transient — the claim under test is the
    # steady-state per-step cost
    s_on = TrainingSentry(SentryConfig(policy="skip", warmup_steps=4))
    on_i = [0]
    host_us = []    # the sentry's ADDED segments, timed directly

    def run_on(n):
        # the host plane run() performs per healthy step: cap staging,
        # probe readback, EWMA fold. Each added segment is also timed
        # on its own — the step+loss-sync in the middle is exactly the
        # off-arm body, so (t1-t0)+(t3-t2) is the sentry's cost with
        # machine noise on the big step excluded
        ts = []
        for _ in range(n):
            i = on_i[0]
            on_i[0] += 1
            t0 = time.perf_counter()
            probed.set_loss_cap(s_on.loss_cap())
            t1 = time.perf_counter()
            loss = float(np.asarray(probed.step(batch)._value))
            t2 = time.perf_counter()
            gn, ap = np.asarray(probed.last_probe).tolist()
            s_on.observe_step(i, i, loss, gn, ap > 0.0)
            t3 = time.perf_counter()
            host_us.append(((t1 - t0) + (t3 - t2)) * 1e6)
            ts.append(t3 - t0)
        return ts

    # same compiled step, sentry off vs on; interleaved arms (drift
    # hits both equally) and a LOW per-step quantile over all reps:
    # scheduler noise is one-sided (delays only add), so the 2nd
    # percentile tracks the undisturbed step where rep wall clocks
    # accumulate every disturbance. A third off-arm pass rides along
    # as an A/A control — `aa_floor_pct` is what this machine reports
    # when there is NO difference, the error bar on `overhead_pct`
    offs, ons, offs2 = [], [], []
    for _ in range(ab_rounds):
        offs.extend(run_off(ab_block))
        ons.extend(run_on(ab_block))
        offs2.extend(run_off(ab_block))
    p2 = lambda ts: float(np.percentile(ts, 2))
    base_step = p2(offs + offs2)
    base_dt = base_step * ab_steps
    sentry_dt = p2(ons) * ab_steps
    aa_floor = abs(p2(offs2) - p2(offs)) / p2(offs) * 100.0
    host_step_us = float(np.median(host_us))
    tokens = batch_b * seq

    # (b) one injected NaN at step 0 under the rollback policy: the
    # sentry restores the (bootstrap) promoted checkpoint and finishes
    ckdir = tempfile.mkdtemp(prefix="sentry-bench-")
    trainer = make(True)
    # compile outside the timed run, under a zero-cap chaos scope: the
    # poison input only exists in the compiled step when the site is
    # armed at trace time, and cap 0 means this warm step never fires
    with chaos.scoped(seed=7, rates={"train.grad.nan": (1.0, 0)}):
        float(trainer.step(batch))
    restore = {}
    orig_load = trainer.load_checkpoint

    def timed_load(path):
        t0 = time.perf_counter()
        orig_load(path)
        restore["seconds"] = time.perf_counter() - t0
    trainer.load_checkpoint = timed_load

    sentry = TrainingSentry(SentryConfig(policy="rollback",
                                         warmup_steps=4,
                                         promote_after=2))
    t0 = time.perf_counter()
    with chaos.scoped(seed=7, rates={"train.grad.nan": (1.0, 1)}):
        out = sentry.run(trainer, lambda c: batch, steps, ckdir,
                         checkpoint_interval=max(2, steps // 4))
    run_dt = time.perf_counter() - t0
    shutil.rmtree(ckdir, ignore_errors=True)

    return {
        "steps": steps,
        "tokens_per_sec_sentry_off": round(
            tokens * ab_steps / base_dt, 2),
        "tokens_per_sec_sentry_on": round(
            tokens * ab_steps / sentry_dt, 2),
        "overhead_pct": round(
            host_step_us / (base_step * 1e6) * 100.0, 3),
        "host_us_per_step": round(host_step_us, 1),
        "ab_delta_pct": round(
            (sentry_dt - base_dt) / base_dt * 100.0, 2),
        "aa_floor_pct": round(aa_floor, 2),
        "probe_compile_delta_pct": round(
            (base_dt - plain_dt) / plain_dt * 100.0, 2),
        "recover": {"rollbacks": out["rollbacks"],
                    "triggers": out["triggers"],
                    "restore_seconds": round(
                        restore.get("seconds", 0.0), 4),
                    "run_seconds": round(run_dt, 3),
                    "promoted_step": out["promoted_step"]},
    }


def _router_bench():
    """Router hop overhead (ISSUE 10): the SAME /predict workload
    measured direct-to-replica and through a 2-replica ReplicaRouter
    on localhost — the p50/p95 delta is the latency one routing hop
    adds (connect + pick + relay), the number a fleet deployment pays
    per request for health-aware failover. Stdlib + a trivial
    dict->dict predictor: no jax, no chip."""
    import json as _json
    import time
    import urllib.request

    from paddle_tpu.inference.router import ReplicaRouter
    from paddle_tpu.inference.serving import PredictorServer

    def pred(inputs):
        return {"y": np.asarray([[1.0]], np.float32)}

    servers = [PredictorServer(pred).start() for _ in range(2)]
    router = ReplicaRouter(
        [f"127.0.0.1:{s.port}" for s in servers]).start()
    try:
        body = _json.dumps({"inputs": {"x": [[1.0, 2.0]]}}).encode()

        def once(port):
            t0 = time.perf_counter()
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/predict", data=body,
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=30) as resp:
                resp.read()
            return (time.perf_counter() - t0) * 1000.0

        n = 50
        for _ in range(5):                  # warm both paths
            once(servers[0].port)
            once(router.port)
        direct = sorted(once(servers[0].port) for _ in range(n))
        routed = sorted(once(router.port) for _ in range(n))

        def pct(xs, p):
            return xs[min(len(xs) - 1, int(round(p / 100.0
                                                 * (len(xs) - 1))))]

        out = {"requests": n, "replicas": len(servers)}
        for name, xs in (("direct_ms", direct),
                         ("via_router_ms", routed)):
            out[name] = {f"p{p}": round(pct(xs, p), 3)
                         for p in (50, 95)}
        out["added_ms"] = {
            f"p{p}": round(pct(routed, p) - pct(direct, p), 3)
            for p in (50, 95)}
        return out
    finally:
        router.stop()
        for s in servers:
            s.stop()


def _autopilot_bench():
    """Fleet-autopilot control-loop latency (ISSUE 16): how long the
    supervisor takes to put a killed replica back in rotation, how
    long a scale-out lags its trigger, and what a 2-replica rolling
    weight swap costs in wall time and failed requests (the headline
    number: 0). Stdlib + a trivial predictor: no jax, no chip."""
    import json as _json
    import threading
    import time
    import urllib.request

    from paddle_tpu.inference.autopilot import (Autoscaler,
                                                InProcessLauncher,
                                                ReplicaSupervisor,
                                                RolloutController)
    from paddle_tpu.inference.router import ReplicaRouter
    from paddle_tpu.inference.serving import PredictorServer

    def pred(inputs):
        return {"y": np.asarray([[1.0]], np.float32)}

    router = ReplicaRouter()
    launcher = InProcessLauncher(
        lambda slot, version: PredictorServer(
            pred, model_name=f"{slot}@{version}"))
    sup = ReplicaSupervisor(router, launcher, ready_timeout_s=10.0)

    def pump(cond, timeout=15.0):
        end = time.perf_counter() + timeout
        while time.perf_counter() < end:
            router.probe_all()
            sup.tick()
            if cond():
                return True
            time.sleep(0.005)
        return False

    try:
        for i in range(2):
            sup.add_slot(f"r{i}", version="v1")
        router.start(probe=False)
        pump(lambda: router.in_rotation_count() == 2)

        # restart-to-ready: kill r1, measure until back in rotation
        launcher.server("r1").stop()
        t0 = time.perf_counter()
        ok = pump(lambda: sup.slot_state("r1") == "serving")
        restart_s = time.perf_counter() - t0 if ok else None

        # scale-out lag: trigger to new-slot-serving
        asc = Autoscaler(router, sup, max_replicas=3, burn_ticks=1,
                         cooldown_s=0.0,
                         signals=lambda: {"ttft_p95_s": None,
                                          "queue_depth": 1e9,
                                          "shed_rate": 0.0})
        t0 = time.perf_counter()
        asc.tick()
        ok = pump(lambda: sup.slot_state("auto-1") == "serving")
        scale_s = time.perf_counter() - t0 if ok else None
        sup.remove_slot("auto-1")

        # rolling swap under live traffic: duration + failed requests
        body = _json.dumps({"inputs": {"x": [[1.0, 2.0]]}}).encode()
        codes, stop = [], threading.Event()

        def traffic():
            while not stop.is_set():
                req = urllib.request.Request(
                    f"http://127.0.0.1:{router.port}/predict",
                    data=body,
                    headers={"Content-Type": "application/json"})
                try:
                    with urllib.request.urlopen(req, timeout=30) as r:
                        r.read()
                        codes.append(r.status)
                except urllib.error.HTTPError as e:
                    codes.append(e.code)
                except Exception:   # noqa: BLE001 — a hang/reset is a failure to count
                    codes.append(-1)
                time.sleep(0.002)

        th = threading.Thread(target=traffic, daemon=True)
        rc = RolloutController(
            router, sup, step_timeout_s=15.0,
            probe_fn=lambda: (router.probe_all(), sup.tick()))
        th.start()
        t0 = time.perf_counter()
        completed = rc.run("v2")
        rollout_s = time.perf_counter() - t0
        stop.set()
        th.join(timeout=30)
        return {
            "restart_to_ready_s": (round(restart_s, 3)
                                   if restart_s is not None else None),
            "scale_out_lag_s": (round(scale_s, 3)
                                if scale_s is not None else None),
            "rollout_duration_s": round(rollout_s, 3),
            "rollout_completed": bool(completed),
            "rollout_requests": len(codes),
            "rollout_failed_requests": sum(1 for c in codes
                                           if c != 200),
        }
    finally:
        for name in list(sup.slot_names()):
            sup.remove_slot(name)
        router.stop()


def main():
    import jax
    import paddle_tpu
    import paddle_tpu.optimizer as opt
    from paddle_tpu.models import LlamaForCausalLM, LlamaConfig
    from paddle_tpu.models.llama import flops_per_token, tiny_llama_config
    from paddle_tpu.parallel import Trainer, TrainStepConfig

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"

    def _hbm_bytes():
        try:
            stats = dev.memory_stats()
            return int(stats.get("bytes_limit", 0)) or 16e9
        except Exception:
            return 16e9

    if on_tpu:
        # size the model to the chip: params * 14B (bf16 w + fp32 master +
        # adam m,v) must leave headroom for activations (remat on)
        hbm = _hbm_bytes()
        if hbm > 2.5e10:  # v5p/v4-class (95G/32G): TinyLlama-1.1B
            cfg = LlamaConfig(
                vocab_size=32000, hidden_size=2048, intermediate_size=5632,
                num_hidden_layers=22, num_attention_heads=32,
                num_key_value_heads=4, max_position_embeddings=2048,
                rope_theta=10000.0, seq_length=2048, recompute=True,
                use_flash_attention=True,
                # blockwise CE (ISSUE 14): the [B*S, 32000] logits no
                # longer cap the batch; PT_BENCH_LOSS_CHUNK=0 reverts
                loss_chunk=int(os.environ.get("PT_BENCH_LOSS_CHUNK",
                                              512)))
            batch, seq, steps = 8, 2048, 10
        else:            # 16G-class chip (v5e/v6e): ~400M params
            # measured on v5e: activations for this size fit without
            # remat, and skipping the recompute pass is ~10% faster
            cfg = LlamaConfig(
                vocab_size=32000, hidden_size=1280, intermediate_size=3584,
                num_hidden_layers=16, num_attention_heads=20,
                num_key_value_heads=4, max_position_embeddings=2048,
                rope_theta=10000.0, seq_length=2048, recompute=False,
                use_flash_attention=True,
                # ffn fusion measured SLOWER here (split defeats the
                # swiglu epilogue fusion); qkv fusion is neutral-positive
                fuse_attention_qkv=True, fuse_attention_ffn=False,
                loss_chunk=int(os.environ.get("PT_BENCH_LOSS_CHUNK",
                                              512)))
            # batch history: b6 > b4 after the fused CE freed the ~1GB
            # f32 log-softmax residual (r2); b7 > b6 after the in-kernel
            # delta + transposed-lse kernels freed the (b,h,sq,8) f32
            # arrays (r4; b8 measured neutral, no longer thrashing)
            batch, seq, steps = int(os.environ.get("PT_BENCH_BATCH", 7)), \
                2048, 10
    else:
        cfg = tiny_llama_config(recompute=True)
        batch, seq, steps = 4, 32, 3

    paddle_tpu.seed(0)
    model = LlamaForCausalLM(cfg)
    optimizer = opt.AdamW(learning_rate=1e-4,
                          parameters=model.parameters(), weight_decay=0.01)
    trainer = Trainer(model, optimizer,
                      config=TrainStepConfig(compute_dtype="bfloat16"))

    import itertools
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32)
    # HOST batch fed through the sharding-aware device prefetcher
    # (trainer.data_iter -> io/prefetch.py): H2D happens on the prefetch
    # thread overlapped with the previous step's compute, and step()
    # sees already-placed arrays — the measured loop is the overlapped
    # zero-device_put path real input pipelines take (for a synthetic
    # in-memory batch this can only tie the old pre-staged-array loop,
    # never beat it; the win is that the benchmark now measures the
    # production path)
    data = {"input_ids": ids, "labels": ids}
    it = trainer.data_iter(itertools.repeat(data, steps + 1), depth=3)

    # warmup + compile; float() forces a real device sync (through the
    # axon tunnel jax.block_until_ready returns before execution finishes)
    float(trainer.step(next(it)))

    t0 = time.perf_counter()
    for b in it:
        loss = trainer.step(b)
    loss = float(loss)  # sync: the last step's outputs close the chain
    dt = time.perf_counter() - t0
    it.close()

    tokens_per_sec = batch * seq * steps / dt
    ftok = flops_per_token(cfg, seq)
    # recompute replays each layer's forward once: ~8N/token instead of 6N
    if cfg.recompute:
        ftok = ftok * 8.0 / 6.0
    mfu = tokens_per_sec * ftok / _peak_flops(dev) if on_tpu else 0.0
    # round-1/2 continuity: MFU as recorded in rounds 1-2, which scored
    # this chip against the v5p peak (459 TF/s) via a lookup-order bug
    mfu_v5p_ref = tokens_per_sec * ftok / 459e12 if on_tpu else 0.0

    # serving decode microbench (ISSUE 6): the perf trajectory now
    # carries aggregate decode tok/s and KV bytes/slot per attend path
    try:
        decode = _decode_bench(on_tpu)
    except Exception as e:           # noqa: BLE001 — never sink the
        decode = {"error": f"{type(e).__name__}: {e}"}  # train metric

    # fleet heartbeat-publisher overhead (ISSUE 9)
    try:
        fleet = _fleet_bench(trainer, data, steps)
    except Exception as e:           # noqa: BLE001 — never sink the
        fleet = {"error": f"{type(e).__name__}: {e}"}   # train metric

    # replica-router hop overhead (ISSUE 10)
    try:
        router = _router_bench()
    except Exception as e:           # noqa: BLE001 — never sink the
        router = {"error": f"{type(e).__name__}: {e}"}  # train metric

    # prefix-cache cold-vs-warm prefill payoff (ISSUE 11)
    try:
        prefix = _prefix_bench()
    except Exception as e:           # noqa: BLE001 — never sink the
        prefix = {"error": f"{type(e).__name__}: {e}"}  # train metric

    # host-tier restore-vs-cold prefill + suspend/resume (ISSUE 18)
    try:
        kvtier = _kvtier_bench()
    except Exception as e:           # noqa: BLE001 — never sink the
        kvtier = {"error": f"{type(e).__name__}: {e}"}  # train metric

    # multi-tenant weighted-fair slot split (ISSUE 13)
    try:
        tenant = _tenant_bench()
    except Exception as e:           # noqa: BLE001 — never sink the
        tenant = {"error": f"{type(e).__name__}: {e}"}  # train metric

    # disaggregated prefill/decode handoff A/B (ISSUE 20)
    try:
        disagg = _disagg_bench()
    except Exception as e:           # noqa: BLE001 — never sink the
        disagg = {"error": f"{type(e).__name__}: {e}"}  # train metric

    # fused-vs-dense train loss path + phase attribution (ISSUE 14)
    try:
        train_breakdown = _train_breakdown(on_tpu)
    except Exception as e:           # noqa: BLE001 — never sink the
        train_breakdown = {"error": f"{type(e).__name__}: {e}"}

    # decomposed-FSDP-collective overlap A/B (ISSUE 19)
    try:
        overlap = _overlap_bench(on_tpu)
    except Exception as e:           # noqa: BLE001 — never sink the
        overlap = {"error": f"{type(e).__name__}: {e}"}

    # fleet-autopilot control-loop latency (ISSUE 16)
    try:
        autopilot = _autopilot_bench()
    except Exception as e:           # noqa: BLE001 — never sink the
        autopilot = {"error": f"{type(e).__name__}: {e}"}

    # training-sentry probe overhead + time-to-recover (ISSUE 17)
    try:
        sentry = _sentry_bench(on_tpu)
    except Exception as e:           # noqa: BLE001 — never sink the
        sentry = {"error": f"{type(e).__name__}: {e}"}

    print(json.dumps({
        "metric": "llama1b_pretrain_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 2),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.40, 4) if on_tpu else 0.0,
        "extra": {"mfu": round(mfu, 4),
                  "mfu_v5p_ref": round(mfu_v5p_ref, 4),
                  "loss": round(float(loss), 4),
                  "device": getattr(dev, "device_kind", str(dev)),
                  "batch": batch, "seq": seq, "steps": steps,
                  "decode": decode, "fleet": fleet, "router": router,
                  "prefix": prefix, "kvtier": kvtier,
                  "tenant": tenant, "disagg": disagg,
                  "train_breakdown": train_breakdown,
                  "overlap": overlap,
                  "autopilot": autopilot, "sentry": sentry},
    }))


if __name__ == "__main__":
    import sys
    if "--overlap-ab" in sys.argv:
        # child mode for _overlap_bench's forced-device re-exec: ONE
        # JSON line on stdout, nothing else
        print(json.dumps(_overlap_ab()))
    else:
        main()
